"""Continuous batching: a slot-pooled decode loop for LLM serving.

The serving structure that keeps the MXU busy under ragged traffic:

* a fixed pool of B slots, each owning one row of the batched KV cache;
* admission: a new request prefills into a free slot (single-row
  forward, scattered into the pooled cache);
* every tick, ONE jitted decode step advances ALL active slots — each
  at its own depth via the vector ``cache_len`` path of the model;
* finished slots free immediately and new requests join mid-flight —
  no waiting for the longest sequence in a static batch.

Everything is static-shape: the pooled cache is [L, B, Hkv, max_seq, D],
the tick input is [B, 1], inactive slots decode garbage that is never
read.  Greedy outputs are verified identical to per-request
``generate()`` in tests.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..analysis import costmodel
from ..telemetry import health
from ..telemetry.events import RECORDER
from ..models import transformer
from . import metrics

log = logging.getLogger("tpushare.serving")

#: [B, 2] uint32 key data -> [B] typed PRNG keys, jitted once: the
#: per-call ``jax.vmap(...)`` retrace cost ~0.6 ms on every tick —
#: real money against a sub-3 ms CPU round (and pure waste on TPU).
_wrap_keys = jax.jit(jax.vmap(jax.random.wrap_key_data))


@functools.partial(jax.jit, static_argnames=("cfg", "chunk_len", "moe"),
                   donate_argnums=(2,))
def _prefill_chunk(params, tokens, caches, slot, pos, last_idx, cfg,
                   chunk_len: int, adapters=None, aids=None, moe=None):
    """One prompt chunk into row ``slot`` at cache offset ``pos`` —
    whole-prompt prefill is just the ``pos=0`` single-chunk case, so
    the slice-row/forward/scatter body exists ONCE.

    Slice, forward, and scatter all happen inside one jit (with the
    pool donated), so admission never materializes a second copy of the
    multi-GB cache on the host path; ``slot``/``pos`` are traced.
    Chunked prefill bounds how long a new request can stall decoding
    slots (head-of-line blocking): a long prompt streams through in
    fixed-size pieces interleaved with ticks.  ``tokens`` is padded to
    ``chunk_len`` so one compile serves every like-sized chunk; the
    caller must keep ``pos + chunk_len <= max_seq`` (the in-jit scatter
    CLAMPS its start index — a window past the end would silently
    overwrite earlier real positions).  Within that bound the padded
    tail is harmless: causality keeps real queries from attending it,
    and its garbage K/V occupies positions that the next chunk or the
    decode loop overwrites before they ever become attendable (position
    p is written at length==p before any query attends p).
    ``last_idx`` selects the final REAL position's logits (only the
    last chunk's are consumed).
    """
    row = jax.tree_util.tree_map(
        lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1), caches)
    # kv_write_len = #real tokens in the (padded) chunk: a ROLLING pool
    # drops the padded tail's ring writes (they would wrap onto
    # still-attendable keys); a full-size pool ignores it (padded
    # writes land beyond the real prefix and are overwritten at
    # length==p before attendable).
    logits, row = transformer.forward(
        params, tokens[:, :chunk_len], cfg, kv_caches=row, cache_len=pos,
        kv_write_len=last_idx + 1, adapters=adapters, adapter_ids=aids,
        moe_mesh=moe)
    caches = jax.tree_util.tree_map(
        lambda c, r: jax.lax.dynamic_update_slice_in_dim(c, r, slot, axis=1),
        caches, row)
    return logits[0, last_idx], caches


@dataclasses.dataclass
class _Prefill:
    """A slot mid-prefill (not yet decoding)."""

    request_id: int
    prompt: List[int]
    pos: int             # prompt tokens already in the cache
    max_new: int
    temperature: float
    seed: int
    chunk: int = 64
    eos_id: Optional[int] = None
    top_k: int = 0
    top_p: float = 1.0


def _sample_next(logits, temps, keys, top_ks=None, top_ps=None):
    """Per-slot next token: argmax where temps[i]==0, else categorical
    from softmax(logits/temps[i]) with slot i's own key.  Shared by the
    dense and paged ticks so greedy/sampling semantics cannot drift.

    ``top_ks``/``top_ps`` (passed together or not at all — the "rich"
    sampler) add per-slot top-k and nucleus filtering: slot i's k
    largest logits survive top-k (k<=0 = off), then the nucleus is
    computed over the RENORMALIZED top-k survivors (p>=1 = off) — the
    sequential composition HF/vLLM users expect, so a request setting
    both filters migrates without a distribution shift.  Both operate
    on temperature-scaled probabilities.  The rich path costs one
    [B, V] sort per step, so ticks only compile it in when some live
    slot asked for it (static arg on the tick programs)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
    lf = logits.astype(jnp.float32) / safe_t
    if top_ks is not None:
        v = lf.shape[-1]
        sorted_l = jnp.sort(lf, axis=-1)[:, ::-1]          # descending
        kk = jnp.clip(top_ks, 1, v)
        kth = jnp.take_along_axis(sorted_l, (kk - 1)[:, None], axis=1)
        mask = (top_ks[:, None] > 0) & (lf < kth)
        # nucleus over the top-k-filtered, renormalized distribution:
        # positions >= k in the sorted order are dropped before the
        # softmax, so the cumulative mass is of the SURVIVORS only.
        # (Positional drop vs the value-threshold top-k mask above can
        # differ on exact ties at the kth value — ties stay in the
        # final mask; their mass is just not counted toward p.)
        idx = jnp.arange(v)[None, :]
        sorted_k = jnp.where((top_ks[:, None] > 0) & (idx >= kk[:, None]),
                             -1e30, sorted_l)
        probs = jax.nn.softmax(sorted_k, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose cumulative mass BEFORE them is < p (the
        # smallest prefix reaching p always includes its last member)
        keep = (csum - probs) < top_ps[:, None]
        cut = jnp.min(jnp.where(keep, sorted_k, jnp.inf), axis=-1)
        mask |= (top_ps[:, None] < 1.0) & (lf < cut[:, None])
        lf = jnp.where(mask, -1e30, lf)
    sampled = jax.vmap(
        lambda k, l: jax.random.categorical(k, l))(keys, lf)
    return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)


def _pp_forward(params, tokens, caches, lengths, cfg, pp,
                adapters=None, aids=None, moe=None):
    """The ONE dense decode-forward routing point for the round-21
    pipeline: ``pp`` is the hashable static ``(mesh, n_micro)`` pair
    (None = the exact pre-pp trace — byte-identity by construction).
    When set, the step runs :func:`transformer.forward_pp_decode` —
    the whole GPipe wavefront inside this same single dispatch, each
    stage decoding its microbatch against its LOCAL layer slice of
    params and KV rows.

    Returns ``(logits, caches, expert_load)`` — the round-22 MoE
    threading: ``moe`` is the hashable static ep Mesh (None = the
    replicated gather, which a dense-FFN config traces byte-identically
    to the pre-MoE program), and ``expert_load`` is the dispatch's
    [E] token→expert assignment counts (None for dense-FFN configs and
    for the staged pipeline — the composed stage bodies run the ep
    psum inline, round 24, but the wavefront carry discards per-layer
    load)."""
    if pp is None:
        return transformer.forward(
            params, tokens, cfg, kv_caches=caches, cache_len=lengths,
            adapters=adapters, adapter_ids=aids, moe_mesh=moe,
            return_expert_load=True)
    mesh, n_micro = pp
    logits, caches = transformer.forward_pp_decode(
        params, tokens, cfg, caches, lengths, mesh, n_micro=n_micro,
        adapters=adapters, adapter_ids=aids, moe_mesh=moe)
    return logits, caches, None


@functools.partial(jax.jit, static_argnames=("cfg", "rich", "pp", "moe"),
                   donate_argnums=(2,))
def _tick(params, tokens, caches, lengths, temps, keys, tks, tps, cfg,
          rich: bool = False, adapters=None, aids=None, pp=None,
          moe=None):
    """Advance every slot one token; tokens [B,1], lengths [B].

    Per-slot sampling via :func:`_sample_next` — greedy and sampling
    requests share one tick.  ``rich`` (static) compiles in the
    top-k/top-p filter only when some live slot uses it, so plain
    greedy/temperature serving never pays the [B, V] sort.  The pooled
    cache is donated: XLA updates it in place instead of holding two
    full copies across the hot loop.  ``pp`` (static; see
    :func:`_pp_forward`) swaps the forward for the staged pipeline
    program — None traces byte-identically to the pre-pp tick.  ``moe``
    (static ep Mesh; round 22) threads the expert-parallel path; the
    returned ``load`` stays device-resident (the entry fetches it only
    at the derived-observe cadence, guard-interior).
    """
    logits, caches, load = _pp_forward(params, tokens, caches, lengths,
                                       cfg, pp, adapters=adapters,
                                       aids=aids, moe=moe)
    nxt = _sample_next(logits[:, 0], temps, keys,
                       tks if rich else None, tps if rich else None)
    return nxt, caches, load


def _decode_scan(params, tokens, caches, lengths, temps, keys, tks, tps,
                 incs, cfg, n: int, rich: bool, adapters=None,
                 aids=None, pp=None, moe=None):
    """The fused decode scan BODY (trace-level, not jitted itself) —
    the one definition shared by :func:`_tick_n` and the mixed-step
    program :func:`_tick_mixed`, so the two dispatch flavors cannot
    drift.  See :func:`_tick_n` for the semantics contract.  ``pp``
    routes each step's forward through :func:`_pp_forward` — the
    staged program runs INSIDE the scan body, so the fused round stays
    one dispatch.  A MoE config accumulates the per-step expert load
    through the scan carry (summed [E] counts for the whole chunk;
    None when the config is dense-FFN or the staged pipeline runs —
    the composed wavefront discards per-layer load, round 24)."""
    track_load = bool(getattr(cfg, "n_experts", 0)) and pp is None

    def body(carry, _):
        tok, caches, lengths, keys, lacc = carry
        ks = jax.vmap(jax.random.split)(keys)          # [B,2]: (next, sub)
        logits, caches, load = _pp_forward(params, tok, caches, lengths,
                                           cfg, pp, adapters=adapters,
                                           aids=aids, moe=moe)
        nxt = _sample_next(logits[:, 0], temps, ks[:, 1],
                           tks if rich else None, tps if rich else None)
        if track_load:
            lacc = lacc + load
        return (nxt[:, None], caches, lengths + incs, ks[:, 0], lacc), nxt

    lacc0 = (jnp.zeros((cfg.n_experts,), jnp.float32)
             if track_load else None)
    (_, caches, _, keys, lacc), toks = jax.lax.scan(
        body, (tokens, caches, lengths, keys, lacc0), None, length=n)
    return toks.T, keys, caches, lacc


@functools.partial(jax.jit, static_argnames=("cfg", "n", "rich", "pp",
                                             "moe"),
                   donate_argnums=(2,))
def _tick_n(params, tokens, caches, lengths, temps, keys, tks, tps, incs,
            cfg, n: int, rich: bool = False, adapters=None, aids=None,
            pp=None, moe=None):
    """``n`` decode ticks in ONE device-resident ``lax.scan`` — one host
    round trip (and one ~70 ms tunnel RPC) per ``n`` tokens instead of
    per token, the same fusion :func:`tpushare.serving.generate
    .make_fused_decode` applies to single requests, applied to the whole
    slot pool.

    Bit-identity with the single-step :func:`_tick` loop: each scan step
    runs the identical forward + :func:`_sample_next`, and the per-slot
    PRNG keys are carried through the scan with the SAME
    ``key, sub = split(key)`` sequence the host loop performs — splits
    are deterministic, so any interleaving of ``tick``/``tick_fused``
    yields the same stream.  Returns (tokens [B, n], final keys, caches,
    accumulated expert load — see :func:`_decode_scan`);
    the caller consumes only each slot's first ``remaining`` tokens —
    steps past a finished slot write garbage K/V that is contained
    exactly like an inactive slot's (position p is overwritten at
    length==p before any query attends p, even across slot reuse).

    ``incs`` [B] is each row's per-step length increment: 1 for rows
    that were DECODING at chunk start, 0 for everything else (empty,
    mid-prefill).  A frozen row garbage-writes the same position every
    step instead of wandering pos..pos+n-1 — required for ROLLING
    pools, where a wandering write at position q would wrap onto ring
    slot q % W and clobber the still-attendable key of position q - W
    in a mid-prefill row.  (A write at exactly pos is safe in both
    layouts: ring slot pos % W holds position pos - W, attendable only
    by queries < pos, all already computed.)
    """
    return _decode_scan(params, tokens, caches, lengths, temps, keys,
                        tks, tps, incs, cfg, n, rich, adapters=adapters,
                        aids=aids, pp=pp, moe=moe)


@functools.partial(jax.jit, static_argnames=("cfg", "chunk_len", "n",
                                             "rich", "pp", "moe"),
                   donate_argnums=(7,))
def _tick_mixed(params, p_tokens, p_slots, p_pos, p_last, src_rows,
                src_mask, caches, tokens, lengths, temps, keys, tks, tps,
                incs, cfg, chunk_len: int, n: int, rich: bool = False,
                adapters=None, aids=None, p_aids=None, pp=None,
                moe=None):
    """ONE device program per mixed service round: (a) the pending
    chunks of up to R mid-prefill slots coalesced into a single batched,
    padded prefill forward, then (b) the fused ``n``-step decode scan
    over the whole slot pool — the token-budget mixed step that replaces
    the interleave-two-dispatches policy (one ~70 ms tunnel RPC per
    round instead of 1 + #prefilling).

    Prefill half: ``p_tokens`` [R, C] holds one padded chunk per row,
    ``p_slots``/``p_pos``/``p_last`` its target slot, cache offset, and
    final real index.  The target rows are GATHERED out of the pool,
    prefilled as one [R, C] forward (per-row math identical to the
    per-slot :func:`_prefill_chunk` — batching adds rows, it never
    reorders a row's reductions), and written back with a per-slot
    SELECT: ``src_rows[b]``/``src_mask[b]`` name the prefill row feeding
    slot b (host-computed; live rows target distinct slots).  A PADDED
    row's output is dropped by the select, so its garbage never touches
    the pool — budget-padding buys one compiled program shape for any
    number of mid-prefill slots.  ``kv_write_len`` bounds ROLLING-ring
    commits per row (padded tails are never committed; full-size pools
    ignore it as always).

    Decode half: the identical scan :func:`_tick_n` runs, over the
    POST-prefill pool.  Rows prefilled this round stay frozen
    (``incs``=0) at their post-chunk offset — the same garbage aim the
    sequential advance-then-fuse interleave produces, contained by the
    same argument (the next chunk or the first real decode write
    overwrites position p before any query attends it).  Per-request
    token streams are therefore bit-identical to the sequential path;
    only the round a finished prefill JOINS the scan shifts (the host
    activates it after the dispatch), which no request's own stream can
    observe.

    Returns (chunk-final logits [R, V], decode tokens [B, n], final
    keys, caches, expert load — the ROUND's total: prefill block plus
    decode scan, both halves of the one dispatch).
    """
    rows = jax.tree_util.tree_map(
        lambda c: jnp.take(c, p_slots, axis=1), caches)
    p_logits, rows, p_load = transformer.forward(
        params, p_tokens[:, :chunk_len], cfg, kv_caches=rows,
        cache_len=p_pos, kv_write_len=p_last + 1, adapters=adapters,
        adapter_ids=p_aids, moe_mesh=moe, return_expert_load=True)

    def put(c, r):
        g = jnp.take(r, src_rows, axis=1)
        m = src_mask.reshape((1, -1) + (1,) * (c.ndim - 2))
        return jnp.where(m, g, c)

    caches = jax.tree_util.tree_map(put, caches, rows)
    sel = p_logits[jnp.arange(p_tokens.shape[0]), p_last]       # [R, V]
    toks, keys, caches, load = _decode_scan(
        params, tokens, caches, lengths, temps, keys, tks, tps, incs,
        cfg, n, rich, adapters=adapters, aids=aids, pp=pp, moe=moe)
    if p_load is not None:
        load = p_load if load is None else load + p_load
    return sel, toks, keys, caches, load


def _dense_spec_verify(params, cfg, adapters=None, aids=None, moe=None):
    """The dense slot pool's ``verify`` closure for
    :func:`tpushare.serving.speculative.spec_scan`: one cached forward
    over the ``[B, 1+k]`` blocks at each row's own depth.

    ``kv_write_len``: a ROLLING ring commits the WHOLE 1+k block for
    live rows — rejected tails are masked by the slack ring's position
    reconstruction (``init_kv_caches(ring_slack=k)``), never retracted
    — and commits NOTHING for frozen rows (their garbage verify never
    touches the ring).  Full-size caches ignore the arg as ever: their
    rejected tails sit past the committed length, position-masked until
    the next block rewrites them.
    """
    def verify(blocks, n_ctxs, live, caches):
        logits, caches = transformer.forward(
            params, blocks, cfg, kv_caches=caches, cache_len=n_ctxs,
            kv_write_len=jnp.where(live, blocks.shape[1], 0),
            adapters=adapters, adapter_ids=aids, moe_mesh=moe)
        return logits, caches

    return verify


@functools.partial(jax.jit, static_argnames=("cfg", "k", "ngram",
                                             "n_rounds", "rich", "moe"),
                   donate_argnums=(2,))
def _tick_spec(params, bufs, caches, buf_lens, n_ctxs, next_toks,
               remainings, actives, temps, keys, tks, tps, cfg, k: int,
               ngram: int, n_rounds: int, rich: bool = False,
               adapters=None, aids=None, moe=None):
    """``n_rounds`` of batched PROMPT-LOOKUP speculative decoding in one
    dispatch — the continuous batcher's speculation path (the serving
    integration of :mod:`.speculative`'s single-request while_loop; the
    round body is :func:`tpushare.serving.speculative.spec_scan`,
    shared with the paged twin and the mixed-spec programs).

    Per round, per GREEDY slot: commit the pending known-correct token,
    propose the ``k`` tokens that followed the most recent earlier
    occurrence of the trailing ``ngram`` in that slot's OWN token
    buffer, verify pending+proposal in ONE ``[B, 1+k]`` forward
    (batch-1 decode is weight-bound, so the k extra positions are
    nearly free), and accept the longest agreeing prefix — greedy-exact
    per slot, like the single-request path.  SAMPLING slots ride the
    same forward as plain decode rows (position-0 logits, one key
    split per round — the fused scan's chain), so a mixed greedy/
    sampling pool still takes one dispatch per round.

    ``bufs`` [B, max_seq + k] is each slot's token history (prompt +
    committed output, device-resident so the n-gram scan never leaves
    the chip; the +k tail keeps a near-max_seq row's proposal append
    from clamping into committed history); ``next_toks`` holds each
    slot's pending token (generated, not yet in cache).  ``actives``/
    ``remainings`` freeze exhausted or inactive rows: a frozen row
    re-verifies at a fixed position every round (writes beyond its
    committed length are never attended — the same containment as a
    finished slot in ``_tick_n``).  Works on EVERY dense pool flavor:
    full-size rows mask rejected writes positionally, rolling rings
    carry ``spec_k`` slots of slack (see :func:`_dense_spec_verify`).

    Returns (bufs, buf_lens, n_ctxs, next_toks, produced, keys,
    accepts, spec_lives, caches): ``produced[i]`` counts tokens
    committed into row i's buf this call; the caller drains
    ``bufs[i, old_len : old_len + produced[i]]``.
    """
    from .speculative import spec_scan
    return spec_scan(_dense_spec_verify(params, cfg, adapters, aids,
                                        moe=moe),
                     _sample_next, bufs, buf_lens, n_ctxs, next_toks,
                     remainings, actives, temps, keys, tks, tps, caches,
                     k, ngram, n_rounds, rich)


@functools.partial(jax.jit, static_argnames=("cfg", "chunk_len", "k",
                                             "ngram", "n_rounds",
                                             "rich", "moe"),
                   donate_argnums=(7,))
def _tick_mixed_spec(params, p_tokens, p_slots, p_pos, p_last, src_rows,
                     src_mask, caches, bufs, buf_lens, n_ctxs,
                     next_toks, remainings, actives, temps, keys, tks,
                     tps, cfg, chunk_len: int, k: int, ngram: int,
                     n_rounds: int, rich: bool = False,
                     adapters=None, aids=None, p_aids=None, moe=None):
    """ONE device program per mixed service round WITH speculation: the
    coalesced budget-bounded prefill block (identical to
    :func:`_tick_mixed`'s prefill half), then ``n_rounds`` speculative
    verify rounds over the whole slot pool — spec rows for greedy
    slots, plain decode rows for sampling slots, frozen garbage rows
    for mid-prefill slots (aimed at their POST-chunk offset, exactly
    like the mixed decode scan's ``incs``-frozen rows).  Speculation
    thereby becomes a third co-resident phase of the single-dispatch
    round instead of a mode switch — the admit-while-decode regime
    keeps the round-7 one-dispatch invariant AND the spec multiplier.

    Returns (chunk-final logits [R, V],) + the :func:`_tick_spec`
    outputs.
    """
    rows = jax.tree_util.tree_map(
        lambda c: jnp.take(c, p_slots, axis=1), caches)
    p_logits, rows = transformer.forward(
        params, p_tokens[:, :chunk_len], cfg, kv_caches=rows,
        cache_len=p_pos, kv_write_len=p_last + 1, adapters=adapters,
        adapter_ids=p_aids, moe_mesh=moe)

    def put(c, r):
        g = jnp.take(r, src_rows, axis=1)
        m = src_mask.reshape((1, -1) + (1,) * (c.ndim - 2))
        return jnp.where(m, g, c)

    caches = jax.tree_util.tree_map(put, caches, rows)
    sel = p_logits[jnp.arange(p_tokens.shape[0]), p_last]       # [R, V]

    from .speculative import spec_scan
    out = spec_scan(_dense_spec_verify(params, cfg, adapters, aids,
                                       moe=moe),
                    _sample_next, bufs, buf_lens, n_ctxs, next_toks,
                    remainings, actives, temps, keys, tks, tps, caches,
                    k, ngram, n_rounds, rich)
    return (sel,) + out


#: every reason a CONFIGURED spec_k can fall back to plain decode — the
#: enumerated values of ``tpushare_spec_fallback_total{reason=}``
#: (enum-linted in tests/test_metric_lint.py, the FALLBACK_REASONS
#: pattern): ``ring_margin`` = the windowed page ring lacks the k-token
#: eviction margin a verify block needs (structural, disables spec at
#: service start); ``sampling_only`` = no greedy slot active this round
#: (spec rows exist only for greedy slots, so the round routes through
#: the plain fused path instead of burning k dead lanes per row)
SPEC_FALLBACK_REASONS = ("ring_margin", "sampling_only")

#: the jitted serving entry points the retrace counter watches — every
#: device program a service round can dispatch.  A LIST on purpose:
#: other serving modules (paged.py) register their own jitted programs
#: through :func:`register_jit_entries` so the retrace counter — and
#: the static dispatch auditor's registry cross-check
#: (tpushare.analysis.dispatch_audit) — see every program, not just the
#: dense ones.  Defining a jitted serving program without registering
#: it here fails ``make lint``.
_JIT_ENTRIES = [_wrap_keys, _prefill_chunk, _tick, _tick_n, _tick_mixed,
                _tick_spec, _tick_mixed_spec]


def register_jit_entries(*fns) -> None:
    """Add serving-plane jitted programs to the retrace watch list
    (idempotent).  Called at import by modules that define their own
    device programs (paged.py); the dispatch auditor statically checks
    every ``@jax.jit`` def in the serving plane is covered."""
    for fn in fns:
        if fn not in _JIT_ENTRIES:
            _JIT_ENTRIES.append(fn)

#: every Nth tick runs the derived observations (goodput re-derivation,
#: retrace scan) — cheap enough to stay inline at that cadence, >1% of
#: a tiny-config tick if run per tick
DERIVED_OBSERVE_EVERY = 16

#: per-entry program-cache size at last observation (None until the
#: first _observe_retraces call per process)
_TRACE_BASELINE: Optional[Dict[int, int]] = None


def _observe_retraces() -> None:
    """Mirror jit program-cache GROWTH on the serving entry points into
    ``tpushare_jit_retraces_total``.  The first observation (normally
    right after the first tick) is the baseline — expected first
    compiles never count; every cache entry added after that does.  A
    new static-arg combination (a different fused ``n_steps``, the rich
    sampler flipping on) legitimately adds ONE entry; steady growth
    under stable traffic is the round-7 hazard this counter exists to
    surface (a per-call wrapper re-tracing every tick, invisible at
    ~0.6 ms without it)."""
    global _TRACE_BASELINE
    if not telemetry.enabled():
        return
    sizes = {}
    for fn in _JIT_ENTRIES:
        size_of = getattr(fn, "_cache_size", None)
        if size_of is None:        # jax without the introspection API
            return
        sizes[id(fn)] = size_of()
    if _TRACE_BASELINE is None:
        _TRACE_BASELINE = sizes
        return
    # entries registered AFTER the baseline (a paged service built in a
    # process that already served dense traffic) are baselined at their
    # own first observation instead of counted from zero — their first
    # compiles are as expected as the dense programs' were
    grew = sum(max(0, n - _TRACE_BASELINE[k])
               for k, n in sizes.items() if k in _TRACE_BASELINE)
    newly_seen = any(k not in _TRACE_BASELINE for k in sizes)
    if grew:
        metrics.JIT_RETRACES.inc(grew)
    if grew or newly_seen:
        _TRACE_BASELINE = sizes


@dataclasses.dataclass
class _Slot:
    request_id: int
    length: int          # tokens currently in the slot's cache + pending
    remaining: int       # tokens still to generate
    last_token: int
    output: List[int]    # prompt + generated (completed value)
    prompt_len: int = 0
    temperature: float = 0.0
    key: Optional[jnp.ndarray] = None
    eos_id: Optional[int] = None
    top_k: int = 0                  # 0 = off
    top_p: float = 1.0              # 1.0 = off


class ContinuousBatcher:
    """Synchronous-core continuous batcher (drive ``admit``/``tick``).

    Storage is pluggable via four hooks (``_init_storage``, ``_reserve``/
    ``_release``, ``_prefill_into``, ``_step``); the admission protocol,
    per-slot sampling bookkeeping, and completion logic live here ONCE.
    :class:`~tpushare.serving.paged.PagedContinuousBatcher` overrides
    only the hooks to swap dense rows for a paged pool.
    """

    def __init__(self, params, cfg: transformer.ModelConfig, n_slots: int,
                 mesh=None, rolling_slots: Optional[bool] = None,
                 spec_k: int = 0, adapter_slots: int = 0,
                 adapter_rank: int = 8, adapter_loader=None,
                 pp: int = 1, pp_microbatches: Optional[int] = None):
        """``mesh``: optional ``jax.sharding.Mesh`` for tensor-parallel
        serving — params take the Megatron tp layout
        (:func:`tpushare.parallel.mesh.shard_params`) and KV storage
        shards its kv-head dim, so one decode tick runs SPMD across the
        pod's chips with XLA-inserted collectives.  Host-side control
        flow (slots, admission, sampling bookkeeping) is unchanged:
        sharding is a placement property of the device arrays, not a
        code path.

        ``rolling_slots``: None (default) = AUTO — sliding-window
        configs get a ROLLING W-sized slot pool (each slot's KV storage
        is ``cfg.window`` entries instead of ``cfg.max_seq``:
        max_seq/window× more slots per HBM byte, same outputs); full-
        causal configs get max_seq rows.  Pass False to force max_seq
        rows for a windowed config (the bit-identity reference).

        ``spec_k``: the speculation depth this pool must be able to
        VERIFY (0 = no provisioning).  A rolling pool adds ``spec_k``
        ring slots of slack so a verify block's rejected k-token tail
        evicts only keys already outside every future query's window
        (``init_kv_caches(ring_slack=)``); other storages need no
        provisioning.  ``tick_spec`` itself takes ``k`` per call —
        ``spec_k`` is the capacity bound the storage was built for.

        ``adapter_slots > 0`` builds the multi-adapter LoRA serving
        pool (:class:`tpushare.serving.adapters.AdapterPool`, rank
        ``adapter_rank``): requests may name an adapter at admission,
        every tick flavor gathers each row's adapter inside its ONE
        jitted dispatch, and streams for adapter-0 (base) rows stay
        bit-identical to a pool-less batcher's.  0 (default) threads
        None everywhere — the byte-identical pre-adapter programs.

        ``pp > 1`` serves pipeline-parallel (round 21): the mesh's
        ``pp`` axis partitions the LAYER dim of params, KV storage, and
        the adapter pool (stage-local residency via GSPMD placement —
        value-preserving, so streams are exact), and the steady decode
        step runs the explicit microbatched wavefront program
        (:func:`tpushare.models.transformer.forward_pp_decode`: stage s
        decodes microbatch m while stage s-1 decodes m+1, ONE host
        dispatch per round).  ``pp_microbatches`` fixes the microbatch
        count (must divide ``n_slots``); default = largest divisor of
        ``n_slots`` that is <= ``pp``.  Structural refusals
        (:func:`tpushare.ops.attention.pp_stage_fallback_reason`:
        ``pp_layers``/``pp_storage``) DEMOTE the staged program to
        placement-only — counted, never a crash.  Since round 24 the
        wavefront COMPOSES with tp/sp/ep on one mesh (the stage bodies
        run the per-shard attention reads and the ep psum inline), so
        a composed mesh no longer demotes."""
        self.mesh = mesh
        self.spec_k = max(0, int(spec_k))
        if rolling_slots is None:
            rolling_slots = (cfg.window is not None
                             and cfg.window < cfg.max_seq)
        if rolling_slots and cfg.window is None:
            raise ValueError("rolling_slots needs a sliding-window cfg")
        if (rolling_slots and self.spec_k
                and cfg.window + self.spec_k >= cfg.max_seq):
            # the spec-slack ring would cover the whole context —
            # full-size rows ARE that storage, with the simpler
            # positional-masking containment story
            rolling_slots = False
        self.rolling_slots = bool(rolling_slots)
        self.pp = max(1, int(pp))
        self._pp_reason = None
        self._pp_args = None
        self.pp_microbatches = None
        if self.pp > 1:
            from ..ops.attention import (pp_stage_fallback_reason,
                                         tp_degree, count_attn_fallback)
            if mesh is None or "pp" not in mesh.axis_names:
                raise ValueError("pp > 1 needs a mesh with a 'pp' axis")
            if mesh.shape["pp"] != self.pp:
                raise ValueError(
                    f"mesh 'pp' axis has {mesh.shape['pp']} devices, "
                    f"batcher asked pp={self.pp}")
            if pp_microbatches is not None:
                if n_slots % pp_microbatches:
                    raise ValueError(
                        f"pp_microbatches={pp_microbatches} must divide "
                        f"n_slots={n_slots}")
                n_micro = int(pp_microbatches)
            else:
                # largest divisor of n_slots that keeps the wavefront
                # no deeper than the stage count (bubble fraction
                # (pp-1)/(m+pp-1) only improves with more microbatches,
                # but m > pp buys nothing at decode's uniform cost)
                n_micro = max(m for m in range(1, min(self.pp, n_slots) + 1)
                              if n_slots % m == 0)
            self.pp_microbatches = n_micro
            self._pp_reason = pp_stage_fallback_reason(
                cfg.n_layers, self.pp, tp=tp_degree(mesh, "tp"),
                sp=tp_degree(mesh, "sp"),
                rolling=self._pp_rolling_storage(cfg))
            if self._pp_reason is None:
                self._pp_args = (mesh, n_micro)
            else:
                # structural demotion to placement-only pipeline
                # parallelism: layers still shard over the pp axis (the
                # partitioner legalizes what it must), the staged
                # wavefront program stays off — counted like every
                # other kernel-path demotion
                count_attn_fallback(self._pp_reason)
        # Expert-parallel gate (round 22): a MoE cfg on a mesh with an
        # "ep" axis shards the stacked expert pool over it and threads
        # the mesh as the static ``moe`` operand into every jitted
        # program (the per-layer gather runs shard-local + psum).
        # Structural refusals (ops.experts.expert_fallback_reason:
        # ``ep_experts`` = n_experts % ep) DEMOTE to a replicated
        # pool — counted, never a crash; since round 24 the staged pp
        # program runs the ep psum inside its stage bodies, so pp no
        # longer refuses.  The demoted case
        # must ALSO skip the ep sharding rules: a pool the partitioner
        # has to all-gather per dispatch is strictly worse than
        # replication.
        self._moe_reason = None
        self._moe_args = None
        moe_rules = None
        if getattr(cfg, "n_experts", 0):
            from ..ops.experts import (expert_fallback_reason,
                                       count_expert_fallback)
            from ..ops.attention import tp_degree
            ep = tp_degree(mesh, "ep") if mesh is not None else 1
            if ep > 1:
                self._moe_reason = expert_fallback_reason(
                    cfg.n_experts, ep,
                    pp=self.pp if self._pp_args is not None else 1)
                if self._moe_reason is None:
                    self._moe_args = mesh
                    from ..parallel.mesh import (EXPERT_SHARDING_RULES,
                                                 SHARDING_RULES)
                    moe_rules = (list(EXPERT_SHARDING_RULES)
                                 + list(SHARDING_RULES))
                else:
                    count_expert_fallback(self._moe_reason)
        if mesh is not None:
            from ..parallel.mesh import shard_params
            params = shard_params(
                params, mesh,
                **({"rules": moe_rules} if moe_rules is not None else {}),
                layer_axis="pp" if "pp" in mesh.axis_names else None)
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        # Multi-adapter LoRA pool (round 20): loop-owned like every
        # other batcher internal — admission acquires/loads, release
        # unpins; _slot_adapter maps slot -> pinned pool row (absent =
        # identity/base).  None = the pre-adapter programs, traced
        # byte-identically (the operands thread as empty pytrees).
        self.adapter_pool = None
        if adapter_slots:
            from .adapters import AdapterPool
            self.adapter_pool = AdapterPool(
                cfg, adapter_rank, adapter_slots, mesh=mesh,
                loader=adapter_loader,
                layer_axis=("pp" if mesh is not None
                            and "pp" in mesh.axis_names else None))
        self._slot_adapter: Dict[int, int] = {}
        self.slots: Dict[int, _Slot] = {}      # slot index -> live request
        self.prefilling: Dict[int, _Prefill] = {}   # slot -> mid-prefill
        # round-robin cursor over mid-prefill SLOT ids: when a round's
        # token budget selects fewer chunks than there are prefilling
        # slots, selection resumes after the last slot served, so a
        # long prompt cannot starve later admits (Sarathi-style
        # fairness; see _select_prefill_slots)
        self._prefill_cursor = 0
        self._next_id = 0
        self.completed: Dict[int, List[int]] = {}
        # tick_spec accounting: tokens committed per speculative round —
        # tokens/rounds > 1 is the acceptance win (each round costs one
        # verify forward, like one plain tick)
        self._spec_stats = {"calls": 0, "rounds": 0, "tokens": 0}
        # per-request lifecycle attribution: rid -> accumulated device-
        # time shares by phase + token count, observed into the request
        # histograms at completion (see _acct_credit/_acct_flush)
        self._req_acct: Dict[int, dict] = {}
        # propagated trace contexts: rid -> fleet trace_id (opaque —
        # the wire format lives in telemetry.propagation), threaded
        # alongside rids into dispatch guards/spans and migration
        # blobs; populated only for requests that arrived with one
        self._rid_traces: Dict[int, str] = {}
        # last round's per-expert routed-token counts ([E] device array
        # from the one dispatch, None for non-MoE cfgs / pp-staged
        # rounds) — flushed into tpushare_expert_load on the
        # DERIVED_OBSERVE_EVERY cadence (see _maybe_observe_expert_load)
        self._moe_load = None
        self._tick_count = 0
        self._init_storage()
        # Roofline cost plane (round 23): the analytical card is a pure
        # function of the serving configuration, derived ONCE here (a
        # dict lookup + arithmetic — never per-tick math); guard exits
        # accumulate (steps, tokens, ctx) per phase into _cost_acc and
        # the DERIVED_OBSERVE_EVERY cadence multiplies through the card
        # into the program FLOP/byte counters (see _cost_flush).
        self._cost_card = costmodel.derive_card(self.cost_shape())
        # attended context per token saturates at the attention window
        # (full-causal configs: max_seq)
        self._cost_ctx_cap = int(cfg.window or cfg.max_seq)
        self._cost_acc = {p: [0.0, 0.0, 0.0] for p in health.PHASES}
        self._observe_storage()

    # -- telemetry helpers ---------------------------------------------
    def _observe_storage(self) -> None:
        """Mirror the KV pool's persistent footprint into /metrics: the
        byte gauge is what ``kubectl inspect tpushare --metrics`` and
        the daemon's grant-vs-usage view read, and the ``_info`` gauge
        names the storage dtype (constant 1, Prometheus info idiom) —
        together they make the int8 saving visible off-process."""
        info = self.storage_info()
        metrics.KV_CACHE_BYTES.set(info["pool_bytes"])
        metrics.KV_DTYPE_INFO.clear()
        metrics.KV_DTYPE_INFO.set(1, kv_dtype=info["kv_dtype"])
        metrics.ATTN_KERNEL_INFO.clear()
        metrics.ATTN_KERNEL_INFO.set(
            1, attn_kernel=info.get("attn_kernel", "xla"))
        metrics.KV_STRIPE_SHARDS.set(info.get("sp_shards", 1))
        metrics.PP_STAGES.set(info.get("pp_stages", 1))
        metrics.PP_BUBBLE_FRACTION.set(
            info.get("pp_bubble_fraction", 0.0))
        metrics.EXPERT_POOL_BYTES.set(info.get("expert_pool_bytes", 0))
        metrics.MOE_EXPERTS.set(info.get("n_experts", 0))

    def _observe_tick(self, t0: float) -> None:
        """Record one tick's wall time and the post-tick occupancy."""
        metrics.TICK_DURATION.observe(time.perf_counter() - t0)
        metrics.OCCUPANCY.set(
            len(self.slots) / self.n_slots if self.n_slots else 0.0)
        self._acct_flush()
        self._tick_count += 1
        if self._tick_count % DERIVED_OBSERVE_EVERY == 0:
            # derived/diagnostic observations on a throttle, not per
            # tick: the goodput gauge re-derives from histogram sums
            # (three locks) and the retrace scan walks six program
            # caches — ~40us together, which is >1% of a SMALL model's
            # tick and pure waste at that cadence (/metrics re-derives
            # utilization at scrape time anyway, and retrace growth is
            # a trend, not a per-tick event)
            health.refresh_device_utilization()
            self._cost_flush()
            _observe_retraces()

    def _complete(self, rid: int, output: List[int]) -> None:
        """The ONE completion bookkeeping site (every tick flavor and the
        instant-finish admission path funnel through it)."""
        self.completed[rid] = output
        metrics.COMPLETIONS.inc()
        # the finishing dispatch already carried the trace; delivery
        # happens host-side, so the context's batcher life ends here
        self._rid_traces.pop(rid, None)
        acct = self._req_acct.get(rid)
        if acct is not None:
            # observed at the next _acct_flush, not here: the dispatch
            # that finished this request is still inside its guard, so
            # its device-time share has not been credited yet
            acct["done_tokens"] = max(0, len(output) - acct["prompt_len"])

    # -- per-request device-time attribution ---------------------------
    def _rids(self, prefilling: bool = False) -> List[int]:
        """Request IDs riding the next dispatch (decoding slots, plus
        mid-prefill ones when asked) — what dispatch-guard flight events
        and trace spans carry, so a stall names its victims."""
        rids = [s.request_id for s in self.slots.values()]
        if prefilling:
            rids += [p.request_id for p in self.prefilling.values()]
        return rids

    def _traces(self, rids: List[int]) -> List[str]:
        """The distinct propagated trace ids among ``rids`` — the
        cross-process correlators dispatch-guard flight events and
        trace spans carry next to the rids (args/events only, NEVER
        metric labels — lint-enforced).  Empty for untraced traffic,
        so the common single-process path records nothing extra."""
        tr = self._rid_traces
        if not tr:
            return []
        seen = []
        for r in rids:
            t = tr.get(r)
            if t is not None and t not in seen:
                seen.append(t)
        return seen

    def _acct_open(self, rid: int, prompt_len: int) -> None:
        if telemetry.enabled():
            self._req_acct[rid] = {"prefill_s": 0.0, "decode_s": 0.0,
                                   "prompt_len": prompt_len,
                                   "done_tokens": None}

    def _acct_credit(self, device_s: Optional[float],
                     decode_rids: List[int],
                     prefill_rids: List[int] = ()) -> None:
        """Split one guard's measured device residency equally across
        the requests that rode the dispatch (decoding participants book
        it as decode, prefilling ones as prefill — the mixed round's
        one program serves both halves, so an exact per-phase split
        does not exist; the equal split is documented in DESIGN.md)."""
        if device_s is None:
            return
        n = len(decode_rids) + len(prefill_rids)
        if not n:
            return
        share = device_s / n
        for rid in decode_rids:
            acct = self._req_acct.get(rid)
            if acct is not None:
                acct["decode_s"] += share
        for rid in prefill_rids:
            acct = self._req_acct.get(rid)
            if acct is not None:
                acct["prefill_s"] += share

    def _acct_flush(self) -> None:
        """Observe and drop completed requests' accumulated attribution
        (runs at tick granularity; completion marks, flush observes —
        so the completing dispatch's own share is included)."""
        if not self._req_acct:
            return
        done = [rid for rid, a in self._req_acct.items()
                if a["done_tokens"] is not None]
        for rid in done:
            a = self._req_acct.pop(rid)
            metrics.REQUEST_DEVICE_TIME.observe(a["prefill_s"],
                                                phase="prefill")
            metrics.REQUEST_DEVICE_TIME.observe(a["decode_s"],
                                                phase="decode")
            metrics.GENERATED_TOKENS.inc(a["done_tokens"])

    # -- roofline cost accounting (round 23) ----------------------------
    def _cost_ctx_ramp(self, pos0: int, n: int) -> int:
        """Total attended context positions across ``n`` consecutive
        tokens whose FIRST sits at cache position ``pos0`` (attending
        ``pos0 + 1`` positions, itself included), saturating at the
        attention window — the arithmetic-series half of the card's
        ``ctx`` count, host-side integer math only."""
        cap = self._cost_ctx_cap
        a = pos0 + 1
        if a >= cap:
            return n * cap
        m = min(n, cap - a + 1)
        return m * a + m * (m - 1) // 2 + (n - m) * cap

    def _cost_note(self, phase: str, steps: float, tokens: float,
                   ctx: float) -> None:
        """Accumulate one guarded dispatch's (scan steps, real tokens,
        attended context) under ``phase`` — three float adds on the hot
        path; the card multiply happens at the DERIVED_OBSERVE_EVERY
        cadence in :meth:`_cost_flush` (the round-11 overhead guard
        covers this site)."""
        if telemetry.enabled():
            acc = self._cost_acc[phase]
            acc[0] += steps
            acc[1] += tokens
            acc[2] += ctx

    def _cost_spec_counts(self, n_rounds: int, k: int):
        """(verify-row tokens, attended context) of ``n_rounds`` spec
        rounds over the current slots: greedy slots verify ``1 + k``
        rows per round (the spec row multiplier), sampling slots ride
        the dispatch as plain decode rows."""
        toks = ctx = 0
        for s in self.slots.values():
            rows = (1 + k) if s.temperature == 0.0 else 1
            toks += rows * n_rounds
            ctx += rows * self._cost_ctx_ramp(s.length, n_rounds)
        return toks, ctx

    def _cost_flush(self) -> None:
        """Multiply the accumulated counts through the cost card into
        the program FLOP / HBM-byte / ICI-byte counters and re-derive
        the roofline gauges — cadence-throttled like the goodput
        re-derivation it rides next to."""
        card = self._cost_card
        ici = 0.0
        for phase, acc in self._cost_acc.items():
            steps, tokens, ctx = acc
            if not steps and not tokens:
                continue
            metrics.PROGRAM_FLOPS.inc(card.flops(steps, tokens, ctx),
                                      phase=phase)
            metrics.PROGRAM_HBM_BYTES.inc(
                card.hbm_bytes(steps, tokens, ctx), phase=phase)
            ici += card.ici_bytes(steps, tokens)
            acc[0] = acc[1] = acc[2] = 0.0
        if ici:
            metrics.ICI_BYTES.inc(ici)
        metrics.refresh_roofline()

    def flush_cost(self) -> None:
        """Flush residual cost accumulations into the program FLOP /
        HBM / ICI counters NOW.  The steady-state flush rides the
        DERIVED_OBSERVE_EVERY cadence in ``_observe_tick`` — a server
        that stops (or goes idle) before serving 16 rounds would
        otherwise report zero work forever.  Idempotent (the
        accumulators drain); call from the thread that ticks."""
        self._cost_flush()

    def _observe_prefill(self) -> None:
        """Mirror the mid-prefill queue depth into /metrics (every site
        that grows or shrinks ``self.prefilling`` calls this)."""
        metrics.PREFILL_QUEUE_DEPTH.set(len(self.prefilling))

    # -- storage hooks -------------------------------------------------
    def _init_storage(self) -> None:
        self.caches = transformer.init_kv_caches(
            self.cfg, batch=self.n_slots, rolling=self.rolling_slots,
            ring_slack=self.spec_k)
        if self.mesh is not None:
            from ..parallel.mesh import shard_kv_storage
            self.caches = shard_kv_storage(
                self.caches, self.mesh,
                layer_axis=("pp" if "pp" in self.mesh.axis_names
                            else None))

    def storage_info(self) -> dict:
        """HBM accounting for the slot pool: what one slot costs and how
        many slots a GiB of KV budget buys — the economics the rolling
        pool changes (window-sized slots: max_seq/window× more slots
        per byte for sliding-window models) and the int8 KV cache
        changes again (~2x slots per byte at any slot size; all byte
        math through :func:`tpushare.ops.quant.kv_cache_bytes`, so
        reservation/gauges/reporting share one dtype-aware model)."""
        from ..ops.quant import kv_cache_bytes
        cfg = self.cfg
        # a rolling pool provisioned for speculation carries spec_k ring
        # slots of slack (see __init__) — price what was allocated
        slot_tokens = (min(cfg.window + self.spec_k, cfg.max_seq)
                       if self.rolling_slots else cfg.max_seq)
        bytes_per_slot = kv_cache_bytes(cfg, slot_tokens)
        # dense slot reads never route through the paged dispatcher, so
        # the read path is the XLA dense cached_attention regardless of
        # cfg.attn_kernel — report what actually runs
        info = {"kind": "rolling" if self.rolling_slots else "dense",
                "attn_kernel": "xla",
                "kv_dtype": cfg.kv_dtype,
                "slot_tokens": int(slot_tokens),
                "bytes_per_slot": int(bytes_per_slot),
                "slots_per_gib": (2 ** 30) // bytes_per_slot,
                "pool_bytes": int(bytes_per_slot * self.n_slots)}
        info.update(self._pp_storage_info(info["pool_bytes"]))
        if self.adapter_pool is not None:
            # the SECOND HBM pool class (round 20): adapter residency
            # economics next to the KV pool's
            info.update(self.adapter_pool.storage_info())
        info.update(self._expert_storage_info())
        return info

    def cost_shape(self) -> dict:
        """This batcher's configuration as the plain dict
        :func:`tpushare.analysis.costmodel.derive_card` prices — model
        dims by value, dtype by NAME, storage geometry from
        :meth:`storage_info`, and EFFECTIVE mesh degrees (a demoted
        gate reports 1, mirroring what the programs actually run).
        ``cross_check_live`` builds a card from this dict and pins its
        ``predicted`` bytes against ``storage_info()`` key-for-key, so
        the two surfaces cannot drift silently."""
        cfg = self.cfg
        info = self.storage_info()
        from ..ops.attention import tp_degree
        shape = {
            "vocab": cfg.vocab, "d_model": cfg.d_model,
            "n_layers": cfg.n_layers, "n_heads": cfg.n_heads,
            "n_kv_heads": cfg.n_kv_heads, "head_dim": cfg.head_dim,
            "d_ff": cfg.d_ff, "max_seq": cfg.max_seq,
            "window": cfg.window,
            "dtype": jnp.dtype(cfg.dtype).name,
            "kv_dtype": cfg.kv_dtype,
            "n_experts": getattr(cfg, "n_experts", 0),
            "moe_top_k": getattr(cfg, "moe_top_k", 1),
            "moe_every": getattr(cfg, "moe_every", 1),
            "kind": info["kind"],
            "attn_kernel": info.get("attn_kernel", "xla"),
            "n_slots": self.n_slots,
            "tp": tp_degree(self.mesh, "tp") if self.mesh is not None
                  else 1,
            "sp": info.get("sp_shards", 1),
            "pp": self.pp,
            "pp_staged": self._pp_args is not None,
            "ep": info.get("ep_shards", 1),
            "spec_k": self.spec_k,
            "adapter_rank": (self.adapter_pool.rank
                             if self.adapter_pool is not None else 0),
        }
        if info["kind"] == "paged":
            shape["page_tokens"] = info["page_tokens"]
            shape["n_pages"] = info["n_pages"]
        else:
            shape["slot_tokens"] = info["slot_tokens"]
        return costmodel.normalize_shape(shape)

    def _expert_storage_info(self) -> dict:
        """Expert-pool residency economics (round 22), shared by the
        dense and paged ``storage_info``: the THIRD HBM pool class —
        the stacked expert weights a MoE cfg keeps resident.  With the
        ep gate admitted the pool shards its expert axis, so per-shard
        bytes divide by the mesh's ep degree; demoted (``ep_experts``)
        or mesh-less configs hold the whole pool replicated."""
        cfg = self.cfg
        if not getattr(cfg, "n_experts", 0):
            return {}
        from ..ops.attention import tp_degree
        from ..ops.experts import expert_pool_bytes
        pool = expert_pool_bytes(cfg)
        ep = (tp_degree(self.mesh, "ep")
              if self._moe_args is not None else 1)
        info = {"n_experts": int(cfg.n_experts),
                "moe_top_k": int(cfg.moe_top_k),
                "expert_pool_bytes": int(pool),
                "ep_shards": int(ep),
                "expert_pool_bytes_per_shard": int(pool // ep)}
        if self._moe_reason is not None:
            info["expert_fallback_reason"] = self._moe_reason
        return info

    def _pp_storage_info(self, pool_bytes: int) -> dict:
        """Pipeline-stage residency economics (round 21), shared by the
        dense and paged ``storage_info``: how the layer partition
        splits the KV pool across stages.  A layer count the stage
        count does not divide legalizes to REPLICATION (every stage
        holds the whole pool — and the staged program is refused with
        ``pp_layers``), so per-stage bytes only shrink when the
        partition is real."""
        from ..parallel.mesh import stage_layer_ranges
        from ..parallel.pipeline import pp_bubble_fraction
        pp = self.pp
        divides = self.cfg.n_layers % pp == 0
        info = {"pp_stages": pp,
                "pool_bytes_per_stage": int(
                    pool_bytes // pp if divides else pool_bytes),
                "stage_layer_ranges": stage_layer_ranges(
                    self.cfg.n_layers, pp)}
        if pp > 1:
            info["pp_fallback_reason"] = self._pp_reason
            info["pp_microbatches"] = self.pp_microbatches
        info["pp_bubble_fraction"] = (
            pp_bubble_fraction(pp, self.pp_microbatches)
            if self._pp_args is not None else 0.0)
        return info

    def _pp_rolling_storage(self, cfg) -> bool:
        """Whether this storage recycles KV in place (the ``pp_storage``
        structural gate): a rolling write's eviction arithmetic couples
        rows across wavefront ticks, which the stage-local microbatch
        slices cannot honor.  The paged subclass adds the windowed page
        ring."""
        return self.rolling_slots

    def _reserve(self, slot: int, prompt_len: int, max_new: int,
                 prompt: Optional[List[int]] = None) -> bool:
        """Claim per-request storage; False = backpressure (no admit).
        ``prompt`` rides along for storages that can share it (the paged
        prefix cache); dense rows are pre-reserved and ignore it."""
        return True

    def _prefill_start(self, slot: int) -> int:
        """First prompt position admission must actually PREFILL —
        storages serving a cached prefix (paged prefix cache) return
        its length; everything else starts at 0."""
        return 0

    def _release(self, slot: int) -> None:
        """Return per-request storage on completion."""
        self._release_adapter(slot)

    def _prefill_into(self, slot: int, tokens, prompt_len: int):
        """Whole-prompt prefill = one chunk at pos 0; returns [V] logits
        at the prompt's last position."""
        adapters, aids = self._adapter_operands(
            [self._slot_adapter.get(slot, 0)])
        logits, self.caches = _prefill_chunk(
            self.params, tokens, self.caches, slot, 0, prompt_len - 1,
            self.cfg, prompt_len, adapters=adapters, aids=aids,
            moe=self._expert_operands())
        return logits

    def _step(self, tokens, lengths, temps, keys, tks, tps, rich,
              ads=None):
        adapters, aids = self._adapter_operands(ads)
        nxt, self.caches, self._moe_load = _tick(
            self.params, tokens, self.caches, lengths, temps, keys,
            tks, tps, self.cfg, rich, adapters=adapters, aids=aids,
            pp=self._pp_args, moe=self._expert_operands())
        return nxt

    def _step_n(self, tokens, lengths, temps, keys, tks, tps, incs, rich,
                n_steps: int, ads=None):
        adapters, aids = self._adapter_operands(ads)
        toks, keys, self.caches, self._moe_load = _tick_n(
            self.params, tokens, self.caches, lengths, temps, keys,
            tks, tps, incs, self.cfg, n_steps, rich, adapters=adapters,
            aids=aids, pp=self._pp_args, moe=self._expert_operands())
        return toks, keys

    def _prefill_chunk_into(self, slot: int, padded_tokens, pos: int,
                            last_idx: int, chunk_len: int):
        """One padded prompt chunk into the slot's cache; returns the
        logits at ``last_idx`` (the chunk's final real position)."""
        adapters, aids = self._adapter_operands(
            [self._slot_adapter.get(slot, 0)])
        logits, self.caches = _prefill_chunk(
            self.params, jnp.asarray(padded_tokens), self.caches,
            slot, pos, last_idx, self.cfg, chunk_len, adapters=adapters,
            aids=aids, moe=self._expert_operands())
        return logits

    # -- session migration capability ----------------------------------
    def can_migrate(self) -> bool:
        """Whether this storage supports session export/import (the
        KV-page migration plane).  Only the PAGED pools do: pages are
        the unit the wire format moves; a dense slot row has no
        page-granular identity to rebuild on a receiver."""
        return False

    def export_session(self, rid: int) -> bytes:
        raise ValueError("session migration requires paged storage "
                         "(pass page_size)")

    def import_session(self, blob: bytes,
                       rid: Optional[int] = None) -> Optional[int]:
        raise ValueError("session migration requires paged storage "
                         "(pass page_size)")

    def pop_session(self, rid: int) -> None:
        raise ValueError("session migration requires paged storage "
                         "(pass page_size)")

    # ------------------------------------------------------------------
    def _rich(self) -> bool:
        """True when any live slot needs the top-k/top-p sampler — the
        static flag picking between the two compiled tick programs."""
        return any(s.top_k > 0 or s.top_p < 1.0
                   for s in self.slots.values())

    def free_slots(self) -> List[int]:
        return [i for i in range(self.n_slots)
                if i not in self.slots and i not in self.prefilling]

    def validate_request(self, prompt: List[int],
                         max_new_tokens: int) -> None:
        """Raise ValueError for a request this batcher can NEVER serve.

        Admission's None return means "retry when capacity frees"; this
        must reject everything a retry can't fix (subclasses extend with
        their own hard capacity limits), or a front-end requeue loop
        would head-of-line-block forever on an impossible request.
        """
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.cfg.max_seq:
            raise ValueError("prompt+max_new exceeds max_seq")

    @staticmethod
    def validate_sampling(top_k: int, top_p: float) -> None:
        if top_k < 0:
            raise ValueError("top_k must be >= 0 (0 = off)")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1] (1 = off)")

    # -- multi-adapter serving (round 20) ------------------------------
    def validate_adapter(self, adapter: Optional[str]) -> None:
        """Raise for an adapter request this batcher can NEVER serve
        (no pool configured / malformed name) — pure validation, safe
        from any thread like :meth:`validate_request`."""
        if adapter is None:
            return
        if not isinstance(adapter, str) or not adapter:
            raise ValueError("adapter must be a non-empty string")
        if self.adapter_pool is None:
            raise ValueError("this server runs without an adapter pool "
                             "(pass adapter_slots / --adapter-slots)")

    def adapter_pressure(self, adapter: Optional[str]) -> bool:
        """Read-only: would an admission naming ``adapter`` refuse
        RIGHT NOW for adapter-pool pressure (non-resident name, every
        pool row pinned by an in-flight request)?  A point-in-time
        snapshot safe off-loop — the llm server's 503 admission gate."""
        if not adapter or self.adapter_pool is None:
            return False
        return self.adapter_pool.pressure(adapter)

    def adapter_info(self) -> Optional[dict]:
        """Point-in-time pool snapshot (None without a pool)."""
        if self.adapter_pool is None:
            return None
        return self.adapter_pool.snapshot()

    def _acquire_adapter(self, adapter: Optional[str]) -> Optional[int]:
        """Resolve + PIN an adapter name at admission: 0 for base
        requests, the pinned pool row otherwise, None = pool pressure
        (the admission-backpressure verdict — retry when a slot
        releases its pin)."""
        if adapter is None:
            return 0
        return self.adapter_pool.acquire(adapter)

    def _release_adapter(self, slot: int) -> None:
        """Unpin the slot's adapter (every release path funnels here
        via the storage ``_release`` hooks)."""
        idx = self._slot_adapter.pop(slot, 0)
        if idx and self.adapter_pool is not None:
            self.adapter_pool.release(idx)

    def _adapter_name_of(self, slot: int) -> Optional[str]:
        """The NAME of the adapter pinned to ``slot`` (None = base) —
        what prefix-registry namespacing and session-migration
        metadata carry (pool indices are process-local)."""
        if self.adapter_pool is None:
            return None
        idx = self._slot_adapter.get(slot, 0)
        return self.adapter_pool.name_of(idx) if idx else None

    def adapter_spill_can_help(self) -> bool:
        """Whether exporting a DECODING session could release an
        adapter pin — the ONLY way the spill tier can relieve
        adapter-pool pressure (spilling base-model sessions frees
        pages, never pins).  Loop-thread admission helper: gates the
        spill loop so adapter pressure against purely base-model
        residents does not park unrelated sessions in host RAM for a
        refusal spilling cannot fix."""
        return any(self._slot_adapter.get(i, 0) for i in self.slots)

    def _adapter_ids_array(self, slots=None):
        """[B] (or per-``slots``) adapter pool rows for a dispatch —
        0 (identity) for base rows, empty rows, and pool-less
        batchers."""
        ids = np.zeros((self.n_slots if slots is None else len(slots),),
                       np.int32)
        if self.adapter_pool is not None:
            if slots is None:
                for i, a in self._slot_adapter.items():
                    ids[i] = a
            else:
                for r, i in enumerate(slots):
                    ids[r] = self._slot_adapter.get(int(i), 0)
        return ids

    def _adapter_operands(self, ads):
        """Device operands for the adapter-threaded programs: (stacked
        pool pytree, ids) — or (None, None), which traces the
        byte-identical pre-adapter program.  HOST-side handle passing
        only: the per-row gather runs INSIDE the one jitted dispatch
        (hook-interior — audited by dispatch_audit's adapter-operand
        rule; this helper must never dispatch or fetch)."""
        if self.adapter_pool is None:
            return None, None
        if ads is None:
            ads = np.zeros((self.n_slots,), np.int32)  # all-identity
        return self.adapter_pool.device_operands(), jnp.asarray(ads)

    def _expert_operands(self):
        """The static ``moe`` operand for the MoE-threaded programs: the
        serving mesh when the ep gate admitted expert sharding, else
        None (which traces the replicated gather — byte-identical to a
        mesh-less batcher for a non-MoE cfg).  HOST-side handle passing
        only, like :meth:`_adapter_operands` (hook-interior — audited
        by dispatch_audit's expert-operand rule; must never dispatch or
        fetch)."""
        return self._moe_args

    def _maybe_observe_expert_load(self) -> None:
        """Flush the last round's accumulated per-expert token counts
        into the ``tpushare_expert_load`` histogram — every
        ``DERIVED_OBSERVE_EVERY`` ticks, like the goodput re-derivation
        (the [E] fetch is one tiny transfer, but per-tick it would
        shave the <2% telemetry overhead budget).  Guard-INTERIOR on
        purpose: the fetch drains the in-flight dispatch, so it must
        count as device wait, not host time."""
        if self._moe_load is None:
            return
        if self._tick_count % DERIVED_OBSERVE_EVERY:
            return
        load = np.asarray(self._moe_load)
        total = float(load.sum())
        if total > 0.0:
            # observe each expert's SHARE of the round's routed tokens:
            # a balanced router puts every sample near 1/E, a collapsed
            # one bimodal at 0 and 1 — dimensionless by design
            metrics.EXPERT_LOAD.observe_many((load / total).tolist())

    # -- speculation capability ----------------------------------------
    def spec_fallback_reason(self, k: int) -> Optional[str]:
        """Why ``spec_k=k`` speculation cannot run on THIS storage
        (None = capable) — the REAL capability check that replaced the
        round-5 dense-pool refusals; reasons enumerate
        :data:`SPEC_FALLBACK_REASONS`.  Full-size dense pools are
        always capable (rejected verify writes are masked
        positionally); a ROLLING ring is capable up to the slack it
        was ALLOCATED with (``spec_k`` extra slots, see ``__init__``)
        — a deeper ``k`` would evict still-in-window keys, the same
        eviction-margin hazard as the windowed page ring."""
        if self.rolling_slots and k > self.spec_k:
            return "ring_margin"
        return None

    def _spec_needs_headroom(self) -> bool:
        """Whether a verify block's garbage tail can CLAMP onto real
        cache positions, so requests need ``prompt + max_new + k <=
        max_seq``.  Only the full-size dense pool: its in-jit block
        write is one ``dynamic_update_slice`` whose clamped start would
        overwrite committed, still-attendable keys.  Rolling rings
        commit through the gather-select (never clamps; slack contains
        rejects) and paged tables route past-the-end writes to the
        trash page."""
        return not self.rolling_slots

    def validate_spec_request(self, prompt_len: int, max_new: int,
                              k: int) -> None:
        """Raise for a request THIS storage could never speculate for
        (the submit-side twin of the per-slot checks in
        :meth:`tick_spec`)."""
        if self._spec_needs_headroom() \
                and prompt_len + max_new + k > self.cfg.max_seq:
            raise ValueError(
                f"speculation needs {k} tokens of cache headroom: "
                f"prompt+max_new_tokens+spec_k exceeds "
                f"max_seq={self.cfg.max_seq}")

    def admit(self, prompt: List[int], max_new_tokens: int,
              temperature: float = 0.0,
              seed: int = 0,
              eos_id: Optional[int] = None,
              top_k: int = 0, top_p: float = 1.0,
              adapter: Optional[str] = None,
              trace: Optional[str] = None) -> Optional[int]:
        """Prefill into a free slot; returns request id, or None when the
        pool is FULL (backpressure).  Invalid requests raise instead —
        None must stay unambiguous for retry loops.  ``eos_id`` finishes
        the request EARLY when sampled, releasing the slot — output is
        the prompt + generated tokens up to and including the eos (what
        ``generate(..., eos_id=...)`` yields once its masked tail is
        dropped; asserted in tests).  ``adapter`` names this request's
        LoRA adapter (pool required; pinned resident until release;
        None on pool pressure, like every other backpressure).
        ``trace`` is the request's propagated fleet trace id (opaque;
        rides guards/spans/flight events and migration blobs)."""
        self.validate_request(prompt, max_new_tokens)
        self.validate_sampling(top_k, top_p)
        self.validate_adapter(adapter)
        free = self.free_slots()
        if not free:
            RECORDER.record("admit_refused", reason="no_free_slot",
                            prompt_len=len(prompt))
            return None
        slot = free[0]
        aidx = self._acquire_adapter(adapter)
        if aidx is None:
            RECORDER.record("admit_refused", reason="adapter_pool",
                            prompt_len=len(prompt))
            return None
        if aidx:
            # mapped BEFORE _reserve: the paged prefix-cache lookup
            # namespaces by the slot's adapter
            self._slot_adapter[slot] = aidx
        if not self._reserve(slot, len(prompt), max_new_tokens,
                             prompt=prompt):
            # storage backpressure: the pool's HBM budget said no — the
            # refusal event is the serving-plane grant/refusal record
            self._release_adapter(slot)           # pin rolled back
            RECORDER.record("admit_refused", reason="storage",
                            prompt_len=len(prompt))
            return None
        rid = self._next_id
        self._next_id += 1
        if trace:
            self._rid_traces[rid] = trace
        metrics.ADMISSIONS.inc()
        RECORDER.record("admit", rid=rid, prompt_len=len(prompt),
                        max_new=max_new_tokens, trace=trace)
        self._acct_open(rid, len(prompt))

        tokens = jnp.asarray([prompt], jnp.int32)
        with health.MONITOR.dispatch_guard("prefill",
                                           tokens=len(prompt),
                                           rids=[rid],
                                           traces=self._traces([rid])
                                           ) as g:
            logits_v = self._prefill_into(slot, tokens, len(prompt))
            self._activate(slot, rid, list(prompt), logits_v,
                           max_new_tokens, temperature, seed, eos_id,
                           top_k, top_p)
        self._acct_credit(g.device_s, [], [rid])
        self._cost_note("prefill", 1, len(prompt),
                        self._cost_ctx_ramp(0, len(prompt)))
        self._acct_flush()
        return rid

    def _activate(self, slot: int, rid: int, prompt: List[int], logits_v,
                  max_new_tokens: int, temperature: float, seed: int,
                  eos_id: Optional[int] = None,
                  top_k: int = 0, top_p: float = 1.0) -> None:
        """Prompt fully prefilled: sample the first token and start (or
        finish) decoding — shared by admit() and chunked prefill so the
        two admission paths produce bit-identical streams.  The first
        token goes through the SAME shared sampler as ticks so top-k/p
        semantics cannot drift between admission and decode."""
        key = jax.random.PRNGKey(seed)
        if temperature > 0.0:
            key, sub = jax.random.split(key)
            rich = top_k > 0 or top_p < 1.0
            first = int(_sample_next(
                logits_v[None, :], jnp.asarray([temperature], jnp.float32),
                sub[None, :] if sub.ndim == 1 else jnp.stack([sub]),
                jnp.asarray([top_k], jnp.int32) if rich else None,
                jnp.asarray([top_p], jnp.float32) if rich else None)[0])
        else:
            first = int(jnp.argmax(logits_v))
        # prefill already produced the first generated token
        remaining = max_new_tokens - 1
        output = list(prompt) + [first]
        if remaining == 0 or (eos_id is not None and first == eos_id):
            self._complete(rid, output)
            # release through a REAL slot record, like every other
            # completion — storages that inspect the finished slot at
            # release (the paged prefix cache donates pure-prompt pages)
            # must see max_new=1 / instant-eos requests too
            self.slots[slot] = _Slot(
                request_id=rid, length=len(prompt), remaining=0,
                last_token=first, output=output,
                prompt_len=len(prompt), temperature=temperature)
            self._release(slot)
            del self.slots[slot]
            return
        self.slots[slot] = _Slot(request_id=rid, length=len(prompt),
                                 remaining=remaining, last_token=first,
                                 output=output, prompt_len=len(prompt),
                                 temperature=temperature,
                                 key=key, eos_id=eos_id,
                                 top_k=top_k, top_p=top_p)

    def admit_chunked(self, prompt: List[int], max_new_tokens: int,
                      temperature: float = 0.0, seed: int = 0,
                      chunk: int = 64,
                      eos_id: Optional[int] = None,
                      top_k: int = 0, top_p: float = 1.0,
                      adapter: Optional[str] = None,
                      trace: Optional[str] = None) -> Optional[int]:
        """Admit with the prompt streamed ``chunk`` tokens at a time by
        subsequent :meth:`advance_prefill` calls, so a long prompt never
        stalls decoding slots for more than one chunk's forward (the
        prefill/decode co-location trade).  Same validation and
        backpressure contract as :meth:`admit` (including the
        ``adapter`` pin); outputs are bit-identical to unchunked
        admission.
        """
        self.validate_request(prompt, max_new_tokens)
        self.validate_sampling(top_k, top_p)
        self.validate_adapter(adapter)
        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        free = self.free_slots()
        if not free:
            RECORDER.record("admit_refused", reason="no_free_slot",
                            prompt_len=len(prompt))
            return None
        slot = free[0]
        aidx = self._acquire_adapter(adapter)
        if aidx is None:
            RECORDER.record("admit_refused", reason="adapter_pool",
                            prompt_len=len(prompt))
            return None
        if aidx:
            # mapped BEFORE _reserve (prefix-cache namespacing)
            self._slot_adapter[slot] = aidx
        if not self._reserve(slot, len(prompt), max_new_tokens,
                             prompt=prompt):
            self._release_adapter(slot)           # pin rolled back
            RECORDER.record("admit_refused", reason="storage",
                            prompt_len=len(prompt))
            return None
        rid = self._next_id
        self._next_id += 1
        if trace:
            self._rid_traces[rid] = trace
        metrics.ADMISSIONS.inc()
        RECORDER.record("admit", rid=rid, prompt_len=len(prompt),
                        max_new=max_new_tokens, chunked=True,
                        trace=trace)
        self._acct_open(rid, len(prompt))
        self.prefilling[slot] = _Prefill(
            request_id=rid, prompt=list(prompt),
            pos=self._prefill_start(slot),
            max_new=max_new_tokens, temperature=temperature, seed=seed,
            chunk=chunk, eos_id=eos_id, top_k=top_k, top_p=top_p)
        self._observe_prefill()
        return rid

    def _select_prefill_slots(self, limit: int,
                              eligible=None) -> List[int]:
        """Up to ``limit`` mid-prefill slot ids in ROUND-ROBIN order:
        circular slot order starting at the cursor, which then moves
        past the last slot served.  When every pending slot fits the
        limit this is just a rotation (all advance); when it doesn't,
        the slots skipped this round are FIRST in line next round — no
        slot waits more than ceil(pending/limit) - 1 rounds, and with
        limit >= pending/2 no slot ever waits more than one round."""
        pending = sorted(self.prefilling if eligible is None else eligible)
        if not pending or limit <= 0:
            return []
        start = 0
        for idx, s in enumerate(pending):
            if s >= self._prefill_cursor:
                start = idx
                break
        rotated = pending[start:] + pending[:start]
        picked = rotated[:limit]
        self._prefill_cursor = (picked[-1] + 1) % max(1, self.n_slots)
        return picked

    def _advance_one_prefill(self, slot: int) -> None:
        """One prompt chunk for ONE mid-prefill slot (its own dispatch)
        — the sequential chunk body, also the fallback for windows the
        fixed-width mixed step cannot take (see tick_mixed)."""
        st = self.prefilling[slot]
        n = len(st.prompt)
        # Clamp the padded window at max_seq: the in-jit scatter
        # clamps out-of-range starts, so an over-long window would
        # silently wrap back over real cached positions.  Window
        # sizes stay static-shaped: {chunk, max_seq mod chunk}.
        window = min(st.chunk, self.cfg.max_seq - st.pos)
        end = min(st.pos + window, n)
        piece = st.prompt[st.pos:end]
        padded = np.zeros((1, window), np.int32)
        padded[0, :len(piece)] = piece
        # one guarded window per chunk, but only the FINAL chunk's
        # _activate fetch is a sync point — mid-prompt chunks dispatch
        # async (near-zero wall, intentionally pipelined), so they
        # stall-watch without observing, or the prefill device-time
        # histogram would fill with ~0 samples
        final = end >= n
        pos0 = st.pos
        with health.MONITOR.dispatch_guard(
                "prefill", observe=final, tokens=len(piece),
                rids=[st.request_id],
                traces=self._traces([st.request_id])) as g:
            logits_v = self._prefill_chunk_into(
                slot, padded, st.pos, len(piece) - 1, window)
            st.pos = end
            if end >= n:
                del self.prefilling[slot]
                self._activate(slot, st.request_id, st.prompt, logits_v,
                               st.max_new, st.temperature, st.seed,
                               st.eos_id, st.top_k, st.top_p)
        # mid-prompt chunks dispatch async (device_s is None there, like
        # the phase histogram); only the final chunk's sync point credits
        self._acct_credit(g.device_s, [], [st.request_id])
        self._cost_note("prefill", 1, len(piece),
                        self._cost_ctx_ramp(pos0, len(piece)))
        if final:
            self._acct_flush()

    def advance_prefill(self, max_slots: Optional[int] = None) -> int:
        """Process one chunk for mid-prefill slots — every slot by
        default, or at most ``max_slots`` selected round-robin (the
        fairness contract of :meth:`_select_prefill_slots`).  Returns
        the number of slots still prefilling afterwards."""
        limit = len(self.prefilling) if max_slots is None else max_slots
        for slot in self._select_prefill_slots(limit):
            self._advance_one_prefill(slot)
        self._observe_prefill()
        return len(self.prefilling)

    def _gather_slot_arrays(self):
        """Assemble the per-slot device operands (tokens, lengths, temps,
        key-data) for a tick — shared by the single and fused paths so
        the mid-prefill garbage-write aiming cannot drift between them.
        ``keys[i]`` is slot i's CURRENT key data (unsplit); each caller
        advances the split chain its own way (host split per tick vs
        in-scan split per step — the same deterministic chain).

        A tick unconditionally writes one garbage K/V at lengths[i] for
        every non-active slot.  Empty rows don't care, but a slot
        MID-PREFILL holds real prompt data — aim its garbage write at
        the next chunk's offset, which that chunk's forward overwrites
        before the position ever becomes attendable.  (A fused chunk's
        writes wander pos..pos+n-1; the same position-by-position
        argument contains them.)
        """
        tokens = np.zeros((self.n_slots, 1), np.int32)
        lengths = np.zeros((self.n_slots,), np.int32)
        temps = np.zeros((self.n_slots,), np.float32)
        keys = np.zeros((self.n_slots, 2), np.uint32)
        tks = np.zeros((self.n_slots,), np.int32)
        tps = np.ones((self.n_slots,), np.float32)
        for i, st in self.prefilling.items():
            lengths[i] = st.pos
        for i, s in self.slots.items():
            tokens[i, 0] = s.last_token
            lengths[i] = s.length
            temps[i] = s.temperature
            tks[i] = s.top_k
            tps[i] = s.top_p
            if s.temperature > 0.0:
                keys[i] = np.asarray(jax.random.key_data(s.key))
        return tokens, lengths, temps, keys, tks, tps

    def tick(self) -> int:
        """One decode step for all active slots; returns #active before."""
        if not self.slots:
            return 0
        t0 = time.perf_counter()
        tokens, lengths, temps, keys, tks, tps = self._gather_slot_arrays()
        for i, s in self.slots.items():
            if s.temperature > 0.0:
                s.key, sub = jax.random.split(s.key)
                keys[i] = np.asarray(jax.random.key_data(sub))
        rids = self._rids() if telemetry.enabled() else []
        traces = self._traces(rids)
        with health.MONITOR.dispatch_guard("decode",
                                           active=len(self.slots),
                                           rids=rids,
                                           traces=traces) as g, \
                telemetry.span("batcher.tick", cat="serving",
                               active=len(self.slots), rids=rids,
                               traces=traces):
            nxt = np.asarray(self._step(
                jnp.asarray(tokens), jnp.asarray(lengths),
                jnp.asarray(temps),
                _wrap_keys(jnp.asarray(keys)),
                jnp.asarray(tks), jnp.asarray(tps), self._rich(),
                ads=self._adapter_ids_array()))
            self._maybe_observe_expert_load()
        self._acct_credit(g.device_s, rids)
        n_active = len(self.slots)
        if telemetry.enabled():
            # each slot's token attends its cache depth + itself,
            # window-capped — lengths are pre-increment here
            cap = self._cost_ctx_cap
            self._cost_note("decode", 1, n_active,
                            sum(min(s.length + 1, cap)
                                for s in self.slots.values()))
        for i in list(self.slots):
            s = self.slots[i]
            s.length += 1              # last_token now lives in the cache
            s.last_token = int(nxt[i])
            s.output.append(s.last_token)
            s.remaining -= 1
            if s.remaining <= 0 or (s.eos_id is not None
                                    and s.last_token == s.eos_id):
                self._complete(s.request_id, s.output)
                self._release(i)
                del self.slots[i]
        self._observe_tick(t0)
        return n_active

    def tick_fused(self, n_steps: int) -> int:
        """Up to ``n_steps`` decode ticks in ONE jitted scan (one host
        round trip); returns #active slots before the chunk.

        Token streams are bit-identical to ``n_steps`` calls of
        :meth:`tick` (see :func:`_tick_n`); the two may be interleaved
        freely.  Slots finishing mid-chunk complete at chunk end (their
        surplus steps decode garbage that is never consumed), so a
        fused chunk trades ≤ ``n_steps-1`` ticks of completion/admission
        latency for per-token host-RPC amortization.  Keep ``n_steps``
        fixed (or bucketed) across calls — it is a static arg and every
        distinct value compiles a fresh n-step program.
        """
        if not self.slots:
            return 0
        t0 = time.perf_counter()
        metrics.FUSED_STEPS.inc(n_steps)
        tokens, lengths, temps, keys, tks, tps = self._gather_slot_arrays()
        # rows decoding at chunk start advance one position per step;
        # everything else (empty, mid-prefill) stays FROZEN at its
        # aimed garbage position — see _tick_n on why rolling pools
        # require this
        incs = np.zeros((self.n_slots,), np.int32)
        for i in self.slots:
            incs[i] = 1
        # the guard spans dispatch AND the host fetches below — the
        # fetch is the true barrier, so this is the window that hangs
        # on a dead tunnel and the window device time is measured over
        rids = self._rids() if telemetry.enabled() else []
        traces = self._traces(rids)
        with health.MONITOR.dispatch_guard("decode",
                                           active=len(self.slots),
                                           steps=n_steps,
                                           rids=rids,
                                           traces=traces) as g:
            with telemetry.span("batcher.tick_fused", cat="serving",
                                active=len(self.slots), steps=n_steps,
                                rids=rids, traces=traces):
                toks, new_keys = self._step_n(
                    jnp.asarray(tokens), jnp.asarray(lengths),
                    jnp.asarray(temps),
                    _wrap_keys(jnp.asarray(keys)),
                    jnp.asarray(tks), jnp.asarray(tps), jnp.asarray(incs),
                    self._rich(), n_steps,
                    ads=self._adapter_ids_array())
            toks = np.asarray(toks)
            new_keys = np.asarray(jax.random.key_data(new_keys))
            self._maybe_observe_expert_load()
        self._acct_credit(g.device_s, rids)
        n_active = len(self.slots)
        if telemetry.enabled():
            self._cost_note("decode", n_steps, n_active * n_steps,
                            sum(self._cost_ctx_ramp(s.length, n_steps)
                                for s in self.slots.values()))
        self._drain_fused_tokens(toks, new_keys, n_steps)
        self._observe_tick(t0)
        return n_active

    def _drain_fused_tokens(self, toks, new_keys, n_steps: int) -> None:
        """Consume one fused scan's [B, n] token block: extend every
        decoding slot by its first ``remaining`` tokens, finish at eos,
        and carry the device-advanced keys — the ONE drain shared by
        :meth:`tick_fused` and :meth:`tick_mixed`."""
        for i in list(self.slots):
            s = self.slots[i]
            take = min(n_steps, s.remaining)
            if s.eos_id is not None:
                row = [int(t) for t in toks[i, :take]]
                if s.eos_id in row:
                    # finish AT the eos; the scan's surplus steps past it
                    # decoded garbage that is contained exactly like a
                    # finished slot's (never consumed, overwritten before
                    # attendable) — identical streams to ticking
                    take = row.index(s.eos_id) + 1
            s.output.extend(int(t) for t in toks[i, :take])
            s.length += take
            s.last_token = int(toks[i, take - 1])
            s.remaining -= take
            if s.remaining <= 0 or (s.eos_id is not None
                                    and s.last_token == s.eos_id):
                self._complete(s.request_id, s.output)
                self._release(i)
                del self.slots[i]
            elif s.temperature > 0.0:
                # the device carried key split exactly `take` == n_steps
                # times for a continuing slot — same chain the host loop
                # would have walked
                s.key = jax.random.wrap_key_data(jnp.asarray(new_keys[i]))

    # -- mixed prefill+decode step -------------------------------------
    def _mixed_chunk_len(self, chunk: int) -> int:
        """The mixed round's fixed prefill-window width for this storage
        (paged storage rounds to a page multiple and clamps into the
        windowed ring's margin)."""
        return max(1, chunk)

    def _step_mixed(self, p_tokens, p_slots, p_active, p_pos, p_last,
                    tokens, lengths, temps, keys, tks, tps, incs, rich,
                    chunk_len: int, n_steps: int, ads=None, p_ads=None):
        """THE one device dispatch of a mixed round (storage hook).
        Returns (chunk-final logits [R, V], decode tokens [B, n], final
        keys)."""
        src_rows, src_mask = self._mixed_src(p_slots, p_active)
        adapters, aids = self._adapter_operands(ads)
        _, p_aids = self._adapter_operands(p_ads)
        sel, toks, keys, self.caches, self._moe_load = _tick_mixed(
            self.params, jnp.asarray(p_tokens), jnp.asarray(p_slots),
            jnp.asarray(p_pos), jnp.asarray(p_last),
            jnp.asarray(src_rows), jnp.asarray(src_mask), self.caches,
            tokens, lengths, temps, keys, tks, tps, incs,
            self.cfg, chunk_len, n_steps, rich, adapters=adapters,
            aids=aids, p_aids=p_aids, pp=self._pp_args,
            moe=self._expert_operands())
        return sel, toks, keys

    def _mixed_src(self, p_slots, p_active):
        """The per-slot prefill-row SELECT operands (``src_rows``/
        ``src_mask``) both dense mixed programs share."""
        src_rows = np.zeros((self.n_slots,), np.int32)
        src_mask = np.zeros((self.n_slots,), bool)
        for r in range(len(p_slots)):
            if p_active[r]:
                src_rows[p_slots[r]] = r
                src_mask[p_slots[r]] = True
        return src_rows, src_mask

    # -- speculative step hooks ----------------------------------------
    def _step_spec(self, bufs, buf_lens, n_ctxs, next_toks, remainings,
                   actives, temps, keys, tks, tps, rich, k: int,
                   ngram: int, n_rounds: int, ads=None):
        """THE one device dispatch of a speculative round batch
        (storage hook).  Returns (bufs, produced, next_toks, keys,
        accepts, spec_lives)."""
        adapters, aids = self._adapter_operands(ads)
        (bufs, _, _, next_toks, produced, keys, accepts, lives,
         self.caches) = _tick_spec(
            self.params, bufs, self.caches, buf_lens, n_ctxs, next_toks,
            remainings, actives, temps, keys, tks, tps, self.cfg, k,
            ngram, n_rounds, rich, adapters=adapters, aids=aids,
            moe=self._expert_operands())
        return bufs, produced, next_toks, keys, accepts, lives

    def _step_mixed_spec(self, p_tokens, p_slots, p_active, p_pos,
                         p_last, bufs, buf_lens, n_ctxs, next_toks,
                         remainings, actives, temps, keys, tks, tps,
                         rich, chunk_len: int, k: int, ngram: int,
                         n_rounds: int, ads=None, p_ads=None):
        """THE one device dispatch of a mixed round with speculation
        (storage hook).  Returns (chunk-final logits [R, V],) + the
        :meth:`_step_spec` outputs."""
        src_rows, src_mask = self._mixed_src(p_slots, p_active)
        adapters, aids = self._adapter_operands(ads)
        _, p_aids = self._adapter_operands(p_ads)
        (sel, bufs, _, _, next_toks, produced, keys, accepts, lives,
         self.caches) = _tick_mixed_spec(
            self.params, jnp.asarray(p_tokens), jnp.asarray(p_slots),
            jnp.asarray(p_pos), jnp.asarray(p_last),
            jnp.asarray(src_rows), jnp.asarray(src_mask), self.caches,
            bufs, buf_lens, n_ctxs, next_toks, remainings, actives,
            temps, keys, tks, tps, self.cfg, chunk_len, k, ngram,
            n_rounds, rich, adapters=adapters, aids=aids,
            p_aids=p_aids, moe=self._expert_operands())
        return sel, bufs, produced, next_toks, keys, accepts, lives

    def _plan_mixed_round(self, chunk: int, budget: int):
        """Pack this round's coalesced prefill block under the token
        budget (round-robin selection, fixed [R, C] shape) — the
        planning half shared by :meth:`tick_mixed` and
        :meth:`tick_mixed_spec`.  Returns (block | None, overflow):
        ``block`` is None when no eligible window exists (nothing
        prefilling, or every pending window crosses max_seq) and the
        caller falls back to the sequential composition."""
        C = self._mixed_chunk_len(chunk)
        R = max(1, min(budget // C if budget >= C else 1, self.n_slots))
        S = self.cfg.max_seq
        eligible = [i for i, st in self.prefilling.items()
                    if st.pos + C <= S]
        overflow = [i for i, st in self.prefilling.items()
                    if st.pos + C > S]
        picked = self._select_prefill_slots(R, eligible)
        if not picked:
            return None, overflow
        p_tokens = np.zeros((R, C), np.int32)
        p_slots = np.zeros((R,), np.int32)
        p_active = np.zeros((R,), bool)
        p_pos = np.zeros((R,), np.int32)
        p_last = np.zeros((R,), np.int32)
        p_ads = np.zeros((R,), np.int32)
        plan = []                      # (row, slot, state, chunk end)
        n_real = 0
        for r, i in enumerate(picked):
            st = self.prefilling[i]
            end = min(st.pos + C, len(st.prompt))
            piece = st.prompt[st.pos:end]
            p_tokens[r, :len(piece)] = piece
            p_slots[r] = i
            p_active[r] = True
            p_pos[r] = st.pos
            p_last[r] = len(piece) - 1
            p_ads[r] = self._slot_adapter.get(i, 0)
            plan.append((r, i, st, end))
            n_real += len(piece)
        metrics.MIXED_STEPS.inc()
        metrics.MIXED_PREFILL_TOKENS.inc(n_real)
        metrics.MIXED_BUDGET_UTILIZATION.set(n_real / float(R * C))
        return {"C": C, "p_tokens": p_tokens, "p_slots": p_slots,
                "p_active": p_active, "p_pos": p_pos, "p_last": p_last,
                "p_ads": p_ads, "plan": plan}, overflow

    def _mixed_fallback(self, overflow, t0, decode) -> int:
        """Nothing for the fixed-width block to do this round: advance
        the max_seq-boundary stragglers sequentially and decode with
        ``decode()`` — exactly the sequential reference composition
        (shared by both mixed flavors)."""
        for i in list(overflow):
            if i in self.prefilling:
                self._advance_one_prefill(i)
        self._observe_prefill()
        if self.slots:
            return decode()
        self._observe_tick(t0)
        return 0

    def _finish_mixed_round(self, plan, sel, overflow) -> None:
        """Post-dispatch host half shared by both mixed flavors:
        activate rows whose chunk completed the prompt (fed by the
        dispatch's chunk-final logits; they join the NEXT round), then
        advance boundary stragglers with the narrow sequential chunk
        (rare — only prompts within one chunk of the context limit
        after uneven earlier chunking)."""
        done = [(r, i, st) for r, i, st, end in plan
                if end >= len(st.prompt)]
        if done:
            sel = np.asarray(sel)
            for r, i, st in done:
                del self.prefilling[i]
                self._activate(i, st.request_id, st.prompt, sel[r],
                               st.max_new, st.temperature, st.seed,
                               st.eos_id, st.top_k, st.top_p)
        for i in overflow:
            if i in self.prefilling:
                self._advance_one_prefill(i)
        self._observe_prefill()

    def tick_mixed(self, n_steps: int, chunk: int = 64,
                   budget: int = 128) -> int:
        """One TOKEN-BUDGET mixed prefill+decode round in a single
        device dispatch: coalesce the pending chunks of up to
        ``budget // chunk`` mid-prefill slots (round-robin, so a long
        prompt cannot starve later admits) into one batched prefill
        forward AND run the ``n_steps`` fused decode scan over all
        decoding slots — the same work the sequential
        ``advance_prefill(); tick_fused(n)`` interleave does in
        ``1 + #prefilling`` dispatches.  Returns #decoding slots before
        the round.

        Per-request token streams are bit-identical to the sequential
        path (see :func:`_tick_mixed`); a slot whose prompt completes
        this round is activated host-side after the dispatch and joins
        the NEXT round's scan.  ``budget`` is padded capacity: the
        prefill block is a fixed [R, chunk] shape (R = budget//chunk,
        clamped to the slot count, min 1) so exactly one program shape
        ever compiles — unused rows burn chunk-width FLOPs and are
        discarded.  A slot whose next window would cross ``max_seq``
        (possible only when earlier sequential chunking left ``pos``
        within ``chunk`` of the boundary) cannot ride the fixed-width
        block — it falls back to one narrow sequential chunk after the
        mixed dispatch, preserving the max_seq clamp invariant.
        """
        if not self.prefilling and not self.slots:
            return 0
        t0 = time.perf_counter()
        block, overflow = self._plan_mixed_round(chunk, budget)
        if block is None:
            return self._mixed_fallback(
                overflow, t0, lambda: self.tick_fused(n_steps))
        plan = block["plan"]
        if self.slots:
            # decoder-empty rounds run the scan for shape only — their
            # steps produce nothing, so they don't count (tick_fused
            # returns before counting when no slot decodes)
            metrics.FUSED_STEPS.inc(n_steps)
        # cost counts use PRE-advance offsets (real chunk tokens and the
        # context each attends — padded rows excluded, MFU = goodput)
        if telemetry.enabled():
            p_toks = sum(end - st.pos for _, _, st, end in plan)
            p_ctx = sum(self._cost_ctx_ramp(st.pos, end - st.pos)
                        for _, _, st, end in plan)
        else:
            p_toks = p_ctx = 0
        # Advance host-side offsets BEFORE gathering the decode operands:
        # the scan's frozen garbage write for a row prefilled this round
        # must aim at the POST-chunk offset (the next window, overwritten
        # before attendable) — the same aim the sequential advance-then-
        # fuse interleave produces.
        for _, _, st, end in plan:
            st.pos = end
        # keys carry each slot's CURRENT (unsplit) data — the scan splits
        # in-device, the same chain tick_fused walks
        tokens, lengths, temps, keys, tks, tps = self._gather_slot_arrays()
        incs = np.zeros((self.n_slots,), np.int32)
        for i in self.slots:
            incs[i] = 1
        # guard spans the one dispatch plus this round's lazy fetches —
        # the measured wall of the mixed round, phase-labeled "mixed"
        if telemetry.enabled():
            decode_rids = self._rids()
            prefill_rids = [st.request_id for _, _, st, _ in plan]
        else:
            decode_rids, prefill_rids = [], []
        traces = self._traces(decode_rids + prefill_rids)
        with health.MONITOR.dispatch_guard("mixed",
                                           active=len(self.slots),
                                           prefilling=len(plan),
                                           steps=n_steps,
                                           rids=decode_rids
                                           + prefill_rids,
                                           traces=traces) as g:
            with telemetry.span("batcher.tick_mixed", cat="serving",
                                active=len(self.slots),
                                prefilling=len(plan), steps=n_steps,
                                rids=decode_rids + prefill_rids,
                                traces=traces):
                sel, toks, new_keys = self._step_mixed(
                    block["p_tokens"], block["p_slots"],
                    block["p_active"], block["p_pos"], block["p_last"],
                    jnp.asarray(tokens), jnp.asarray(lengths),
                    jnp.asarray(temps),
                    _wrap_keys(jnp.asarray(keys)),
                    jnp.asarray(tks), jnp.asarray(tps),
                    jnp.asarray(incs), self._rich(), block["C"],
                    n_steps, ads=self._adapter_ids_array(),
                    p_ads=block["p_ads"])
            # Host fetches are the real sync points (CLAUDE.md): fetch
            # ONLY what this round consumes, so pure-prefill rounds
            # with no completions stay fully async and pipeline like
            # sequential chunk dispatches do.
            n_active = len(self.slots)
            if n_active:
                toks = np.asarray(toks)
                new_keys = np.asarray(jax.random.key_data(new_keys))
            self._maybe_observe_expert_load()
        self._acct_credit(g.device_s, decode_rids, prefill_rids)
        if telemetry.enabled():
            # one weight pass for the prefill block + n_steps scan
            # iterations when anything decodes (decoder-empty rounds
            # run the scan for shape only — no goodput, not counted)
            self._cost_note(
                "mixed", (n_steps if n_active else 0) + 1,
                p_toks + n_active * n_steps,
                p_ctx + sum(self._cost_ctx_ramp(s.length, n_steps)
                            for s in self.slots.values()))
        if n_active:
            self._drain_fused_tokens(toks, new_keys, n_steps)
        self._finish_mixed_round(plan, sel, overflow)
        self._observe_tick(t0)
        return n_active

    def cancel(self, rid: int) -> bool:
        """Release request ``rid`` wherever it lives — decoding slot,
        mid-prefill, or the completed buffer — freeing its slot/storage
        immediately.  Returns False when unknown (already drained or
        never admitted).  Owner-thread only, like every batcher method:
        the service loop calls this for abandoned streams so a client
        that disconnected mid-stream does not keep decoding to
        completion in a slot someone else could use."""
        # a cancelled request's partial attribution is dropped, not
        # observed — the request histograms describe COMPLETED lifecycles
        self._req_acct.pop(rid, None)
        self._rid_traces.pop(rid, None)
        for i, s in list(self.slots.items()):
            if s.request_id == rid:
                self._release(i)
                del self.slots[i]
                metrics.CANCELLATIONS.inc()
                return True
        for i, p in list(self.prefilling.items()):
            if p.request_id == rid:
                self._release(i)
                del self.prefilling[i]
                self._observe_prefill()
                metrics.CANCELLATIONS.inc()
                return True
        # completed-but-undelivered: the request already counted as a
        # completion, so dropping its result is NOT a cancellation
        # (admissions == completions + cancellations must reconcile)
        return self.completed.pop(rid, None) is not None

    def _validate_spec_call(self, k: int) -> None:
        """The loud half of a spec call, BEFORE any state mutates: a
        storage that cannot CONTAIN a k-token verify block at all
        (:meth:`spec_fallback_reason` — a slack-less rolling ring, a
        margin-short page ring) raises, and on the full-size dense pool
        every live/mid-prefill request must carry ``k`` tokens of cache
        headroom (see :meth:`_spec_needs_headroom`: the verify-block
        write is one clamping dynamic_update_slice, and the frozen
        garbage write is (1+k) wide too).  The silent alternative is
        corrupted streams — direct batcher-API callers get the loud
        error the service-level fallback replaced.  Both spec entry
        points call this before touching prefill offsets or dispatch
        state, so a raise leaves the batcher exactly as it was."""
        reason = self.spec_fallback_reason(k)
        if reason is not None:
            raise ValueError(
                f"this {self.storage_info()['kind']} storage cannot "
                f"verify k={k} speculative blocks ({reason}); "
                f"provision the batcher with spec_k >= {k} or lower k")
        if not self._spec_needs_headroom():
            return
        S = self.cfg.max_seq
        for i, st in self.prefilling.items():
            if len(st.prompt) + st.max_new + k > S:
                raise ValueError(
                    f"prefilling slot {i}: speculation needs {k} tokens "
                    f"of cache headroom past prompt+max_new (max_seq {S})")
        for i, s in self.slots.items():
            if len(s.output) + s.remaining + k > S:
                raise ValueError(
                    f"slot {i}: speculation needs {k} tokens of cache "
                    f"headroom past prompt+max_new (max_seq {S})")

    def _gather_spec_arrays(self, k: int):
        """Assemble the per-slot operands of a speculative round batch —
        shared by :meth:`tick_spec` and :meth:`tick_mixed_spec` (which
        must gather AFTER advancing prefill offsets, so frozen rows aim
        at their post-chunk position; validation runs separately and
        FIRST, see :meth:`_validate_spec_call`).  ``bufs`` carries a
        ``+k`` tail past max_seq so a near-full row's proposal append
        can never clamp back into committed history."""
        S, B = self.cfg.max_seq, self.n_slots
        bufs = np.zeros((B, S + k), np.int32)
        buf_lens = np.zeros((B,), np.int32)
        n_ctxs = np.zeros((B,), np.int32)
        next_toks = np.zeros((B,), np.int32)
        remainings = np.zeros((B,), np.int32)
        actives = np.zeros((B,), bool)
        temps = np.zeros((B,), np.float32)
        keys = np.zeros((B, 2), np.uint32)
        tks = np.zeros((B,), np.int32)
        tps = np.ones((B,), np.float32)
        for i, st in self.prefilling.items():
            n_ctxs[i] = st.pos           # frozen garbage aim
        for i, s in self.slots.items():
            hist = s.output
            bufs[i, :len(hist) - 1] = hist[:-1]
            buf_lens[i] = len(hist) - 1
            n_ctxs[i] = s.length
            next_toks[i] = s.last_token
            remainings[i] = s.remaining
            actives[i] = True
            temps[i] = s.temperature
            tks[i] = s.top_k
            tps[i] = s.top_p
            if s.temperature > 0.0:
                keys[i] = np.asarray(jax.random.key_data(s.key))
        return (bufs, buf_lens, n_ctxs, next_toks, remainings, actives,
                temps, keys, tks, tps)

    def _spec_operands(self, arrays):
        """Host arrays -> the device operands `_step_spec` /
        `_step_mixed_spec` take (keys wrapped once, jitted)."""
        (bufs, buf_lens, n_ctxs, next_toks, remainings, actives, temps,
         keys, tks, tps) = arrays
        return (jnp.asarray(bufs), jnp.asarray(buf_lens),
                jnp.asarray(n_ctxs), jnp.asarray(next_toks),
                jnp.asarray(remainings), jnp.asarray(actives),
                jnp.asarray(temps), _wrap_keys(jnp.asarray(keys)),
                jnp.asarray(tks), jnp.asarray(tps))

    def _drain_spec(self, bufs_h, produced, next_h, new_keys, accepts,
                    lives, n_rounds: int) -> None:
        """Consume one spec batch's outputs: extend every slot by its
        committed tokens, finish at eos/exhaustion, carry the
        device-advanced sampling keys, and feed the accept-depth
        histogram — the ONE drain shared by :meth:`tick_spec` and
        :meth:`tick_mixed_spec`."""
        for i in list(self.slots):
            s = self.slots[i]
            got = int(produced[i])
            if got == 0:
                continue
            old_len = len(s.output) - 1
            committed = [int(t) for t in bufs_h[i, old_len:old_len + got]]
            # committed[0] re-commits the pending s.output[-1]; the new
            # tokens are committed[1:] plus the fresh pending token
            new_toks = committed[1:] + [int(next_h[i])]
            take = min(len(new_toks), s.remaining)
            new_toks = new_toks[:take]
            if s.eos_id is not None and s.eos_id in new_toks:
                take = new_toks.index(s.eos_id) + 1
                new_toks = new_toks[:take]
            if telemetry.enabled():
                # accept-depth: this slot's live greedy rounds, but
                # ONLY up to the delivered tokens — the device cannot
                # see eos, so its post-eos rounds keep accepting
                # lookup tokens the host discards; counting them would
                # inflate the acceptance distribution on eos-heavy
                # traffic (each live round delivers its pending commit
                # plus its accepts, so the cumulative walk stops where
                # truncation did)
                depths, delivered = [], 0
                for r in range(n_rounds):
                    if not lives[r, i] or delivered >= take:
                        continue
                    depths.append(float(accepts[r, i]))
                    delivered += 1 + int(accepts[r, i])
                if depths:
                    metrics.SPEC_ACCEPT_DEPTH.observe_many(depths)
            s.output.extend(new_toks)
            s.remaining -= take
            s.last_token = s.output[-1]
            # cache coverage: everything except the new pending token
            # (== the device's final n_ctx for untruncated rows)
            s.length = len(s.output) - 1
            self._spec_stats["tokens"] += take
            metrics.SPEC_TOKENS.inc(take)
            if s.remaining <= 0 or (s.eos_id is not None
                                    and s.last_token == s.eos_id):
                self._complete(s.request_id, s.output)
                self._release(i)
                del self.slots[i]
            elif s.temperature > 0.0:
                # the device split this slot's key once per round — the
                # same chain the host/fused paths walk per token
                s.key = jax.random.wrap_key_data(jnp.asarray(new_keys[i]))
        self._spec_stats["rounds"] += n_rounds
        self._spec_stats["calls"] += 1
        metrics.SPEC_ROUNDS.inc(n_rounds)

    def tick_spec(self, n_rounds: int, k: int = 8, ngram: int = 2) -> int:
        """``n_rounds`` of batched prompt-lookup SPECULATIVE decoding in
        one dispatch (see :func:`_tick_spec`); returns #active slots
        before the call.  Greedy-exact: greedy token streams are
        identical to :meth:`tick`/:meth:`tick_fused` and the flavors
        may be interleaved freely, so the service can speculate
        opportunistically.  Runs on EVERY storage flavor — full-size
        dense, rolling ring (spec-slack provisioned, see ``spec_k``),
        and the paged pools via the subclass hook — with sampling slots
        riding the verify forward as plain decode rows (their streams
        stay bit-identical to the fused path's; only GREEDY slots
        speculate).

        Remaining constraint: on the full-size dense pool each request
        needs ``prompt + max_new + k <= max_seq`` of cache headroom
        (rejected tails write up to k past the end and the block write
        clamps); rolling rings and paged tables contain the tail
        without headroom (see DESIGN.md "Speculation on paged pools").
        """
        if not self.slots:
            return 0
        t0 = time.perf_counter()
        self._validate_spec_call(k)
        arrays = self._gather_spec_arrays(k)
        rids = self._rids() if telemetry.enabled() else []
        with health.MONITOR.dispatch_guard("decode",
                                           active=len(self.slots),
                                           spec_rounds=n_rounds,
                                           rids=rids,
                                           traces=self._traces(rids)
                                           ) as g:
            out = self._step_spec(*self._spec_operands(arrays),
                                  self._rich(), k, ngram, n_rounds,
                                  ads=self._adapter_ids_array())
            bufs_h = np.asarray(out[0])
            produced = np.asarray(out[1])
            next_h = np.asarray(out[2])
            new_keys = np.asarray(jax.random.key_data(out[3]))
            accepts = np.asarray(out[4])
            lives = np.asarray(out[5])
        self._acct_credit(g.device_s, rids)
        n_active = len(self.slots)
        if telemetry.enabled():
            toks, ctx = self._cost_spec_counts(n_rounds, k)
            self._cost_note("decode", n_rounds, toks, ctx)
        self._drain_spec(bufs_h, produced, next_h, new_keys, accepts,
                         lives, n_rounds)
        self._observe_tick(t0)
        return n_active

    def tick_mixed_spec(self, n_rounds: int, chunk: int = 64,
                        budget: int = 128, k: int = 8,
                        ngram: int = 2) -> int:
        """One mixed service round with SPECULATION as the decode half:
        the coalesced budget-bounded prefill block plus ``n_rounds``
        speculative verify rounds (spec rows for greedy slots, plain
        decode rows for sampling slots) in ONE device dispatch — the
        round-7 single-dispatch invariant with the speculation
        multiplier riding along (see :func:`_tick_mixed_spec`).  Same
        fairness (round-robin chunk selection), same boundary-straggler
        fallback (which then decodes through :meth:`tick_spec`), same
        activation protocol as :meth:`tick_mixed`; returns #decoding
        slots before the round.
        """
        if not self.prefilling and not self.slots:
            return 0
        t0 = time.perf_counter()
        # validate BEFORE any mutation: a raise here (incapable
        # storage, missing headroom) must leave prefill offsets and the
        # round-robin cursor untouched
        self._validate_spec_call(k)
        block, overflow = self._plan_mixed_round(chunk, budget)
        if block is None:
            return self._mixed_fallback(
                overflow, t0,
                lambda: self.tick_spec(n_rounds, k=k, ngram=ngram))
        plan = block["plan"]
        # cost counts use PRE-advance offsets, like tick_mixed
        if telemetry.enabled():
            p_toks = sum(end - st.pos for _, _, st, end in plan)
            p_ctx = sum(self._cost_ctx_ramp(st.pos, end - st.pos)
                        for _, _, st, end in plan)
        else:
            p_toks = p_ctx = 0
        # advance offsets BEFORE gathering: frozen rows aim their
        # (1+k)-wide garbage verify at the POST-chunk offset, the same
        # aim tick_mixed gives the frozen decode scan
        for _, _, st, end in plan:
            st.pos = end
        arrays = self._gather_spec_arrays(k)
        if telemetry.enabled():
            decode_rids = self._rids()
            prefill_rids = [st.request_id for _, _, st, _ in plan]
        else:
            decode_rids, prefill_rids = [], []
        traces = self._traces(decode_rids + prefill_rids)
        with health.MONITOR.dispatch_guard("mixed",
                                           active=len(self.slots),
                                           prefilling=len(plan),
                                           spec_rounds=n_rounds,
                                           rids=decode_rids
                                           + prefill_rids,
                                           traces=traces) as g:
            with telemetry.span("batcher.tick_mixed_spec", cat="serving",
                                active=len(self.slots),
                                prefilling=len(plan),
                                spec_rounds=n_rounds,
                                rids=decode_rids + prefill_rids,
                                traces=traces):
                out = self._step_mixed_spec(
                    block["p_tokens"], block["p_slots"],
                    block["p_active"], block["p_pos"], block["p_last"],
                    *self._spec_operands(arrays), self._rich(),
                    block["C"], k, ngram, n_rounds,
                    ads=self._adapter_ids_array(),
                    p_ads=block["p_ads"])
            sel = out[0]
            # host fetches only what this round consumes (lazy, like
            # tick_mixed): pure-prefill rounds stay fully async
            n_active = len(self.slots)
            if n_active:
                bufs_h = np.asarray(out[1])
                produced = np.asarray(out[2])
                next_h = np.asarray(out[3])
                new_keys = np.asarray(jax.random.key_data(out[4]))
                accepts = np.asarray(out[5])
                lives = np.asarray(out[6])
        self._acct_credit(g.device_s, decode_rids, prefill_rids)
        if telemetry.enabled():
            toks, ctx = self._cost_spec_counts(n_rounds, k)
            self._cost_note("mixed", (n_rounds if n_active else 0) + 1,
                            p_toks + toks, p_ctx + ctx)
        if n_active:
            self._drain_spec(bufs_h, produced, next_h, new_keys,
                             accepts, lives, n_rounds)
        self._finish_mixed_round(plan, sel, overflow)
        self._observe_tick(t0)
        return n_active

    def run_until_drained(self, max_ticks: int = 10_000) -> None:
        for _ in range(max_ticks):
            if self.prefilling:
                self.advance_prefill()
            if not self.tick() and not self.prefilling:
                return
        raise RuntimeError("batcher did not drain")


#: Thread-confinement manifest for :class:`ContinuousService` — the
#: round-16 "loop-thread private" comments promoted to a DECLARED
#: contract, verified statically by ``tpushare.analysis.confinement``
#: (Layer 3 of ``make lint``).  The model: the service loop thread OWNS
#: the batcher and all ``loop_confined`` state; HTTP-handler threads
#: (llm/daemon/router routes) and other callers are untrusted roots
#: that may only cross into loop state through the ``lock_crossed``
#: command queues (appended under ``self._lock``, drained by the loop).
#: ``join_synced`` methods may touch loop state because they join the
#: loop thread (or prove it dead) first.  ``batcher_readonly`` names
#: the batcher methods that are pure/validating and safe to call from
#: any thread; every other batcher CALL must come from the loop.
#: Reads of loop state from untrusted threads stay legal — they are
#: documented point-in-time snapshots (see :meth:`snapshot`) — only
#: MUTATIONS are confined.  Keep this in sync with ``__init__`` (the
#: checker fails on a manifest name no longer initialized there).
_THREAD_MANIFEST = {
    "class": "ContinuousService",
    "loop_roots": ("_loop",),
    "construction": ("__init__", "start"),
    "join_synced": ("stop",),
    "loop_confined": ("_sinks", "_stream_sinks", "_req_meta",
                      "_handoff_rids", "_migrated_sinks",
                      "_resident_since", "_spill", "_batcher",
                      "_policy_pacer"),
    "lock_crossed": ("_waiting", "_mig_cmds", "_cancels"),
    "batcher_attr": "_batcher",
    # adapter-pool note (round 20): the multi-adapter LoRA pool is
    # LOOP-OWNED state inside the batcher (reached only through
    # ``_batcher``) — acquire/load/evict run at admission and release
    # at completion, both loop-side; handler threads see it only
    # through the read-only snapshots below (``adapter_pressure`` is
    # the llm server's 503 gate, ``validate_adapter``/``adapter_info``
    # pure views), exactly like the page free-list before it.
    "batcher_readonly": ("validate_request", "validate_sampling",
                         "validate_spec_request", "spec_fallback_reason",
                         "can_migrate", "storage_info", "free_slots",
                         "validate_adapter", "adapter_pressure",
                         "adapter_info"),
}


class ContinuousService:
    """Thread-safe front end over :class:`ContinuousBatcher`.

    ``submit`` returns a queue delivering the finished token list; a
    background thread ticks while work exists, admits queued requests as
    slots free, and sleeps when idle.  Greedy and sampling requests mix
    freely (per-slot temperature/keys in the shared tick).
    """

    def __init__(self, params, cfg: transformer.ModelConfig, n_slots: int,
                 page_size: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 prefill_chunk: int = 64,
                 decode_chunk: int = 8,
                 prefill_decode_chunk: Optional[int] = None,
                 mesh=None,
                 spec_k: int = 0,
                 spec_ngram: int = 2,
                 spec_rounds: Optional[int] = None,
                 prefix_cache: bool = False,
                 mixed_step: bool = True,
                 prefill_budget: Optional[int] = None,
                 spill_bytes: Optional[int] = None,
                 policy=None,
                 adapter_slots: int = 0,
                 adapter_rank: int = 8,
                 pp: int = 1,
                 pp_microbatches: Optional[int] = None):
        import os as _os
        import queue as _q
        import threading

        self._q = _q
        # Tenant-policy pacer (serving/policy.py DispatchPacer, or
        # None): installed on the process-global health monitor for
        # the service's lifetime, so every dispatch guard the loop
        # enters paces/debits against this tenant's device-time
        # bucket.  The pacing state itself lives in the pacer (its own
        # _LOCK_GUARDED manifest); the service only owns the install/
        # uninstall lifecycle — start() arms, stop() disarms exactly
        # what it armed.  None = byte-identical pre-policy serving.
        self._policy_pacer = policy
        # MIXED rounds (default): while anything is mid-prefill, each
        # loop iteration is ONE device dispatch — the pending chunks of
        # up to prefill_budget//prefill_chunk slots coalesced into a
        # batched prefill, fused with the decode scan (tick_mixed) —
        # instead of the sequential 1 + #prefilling dispatches.
        # prefill_budget is the per-round prefill TOKEN budget
        # (Sarathi-style); default two chunks' worth.  It is padded
        # capacity: one program shape compiles regardless of how many
        # slots are actually prefilling.  mixed_step=False restores the
        # sequential advance-then-fuse interleave (the bit-identical
        # reference path).
        self._mixed_step = bool(mixed_step)
        # Steady-state decoding runs decode_chunk ticks per host round
        # trip (tick_fused) — the host-RPC amortization that closes most
        # of the per-dispatch vs fused-scan throughput gap.  1 disables
        # fusion.  The trade is ≤ decode_chunk-1 ticks of completion/
        # admission latency per chunk.
        self._decode_chunk = max(1, decode_chunk)
        # spec_k > 0 enables OPPORTUNISTIC prompt-lookup speculation on
        # EVERY storage flavor (dense, rolling ring, paged, page ring,
        # prefix cache; kv_dtype="int8" included): rounds with any
        # greedy slot active route through tick_spec — or, while
        # anything is mid-prefill, through tick_mixed_spec, which fuses
        # the coalesced prefill block WITH the spec rounds into one
        # dispatch — and sampling slots ride those programs as plain
        # decode rows (greedy-only routing: only greedy slots
        # speculate).  A pool that structurally cannot verify k tokens
        # (a windowed page ring without the eviction margin) DISABLES
        # speculation at start with a counted fallback instead of
        # refusing to serve.  spec_rounds defaults to half the decode
        # chunk: at acceptance ~1 token/round speculation matches the
        # fused path's per-dispatch token yield, and beats it as
        # acceptance grows.
        self._spec_k = int(spec_k)
        self._spec_ngram = int(spec_ngram)
        self._spec_rounds = (int(spec_rounds) if spec_rounds is not None
                             else max(1, self._decode_chunk // 2))
        # While any slot is mid-prefill the loop interleaves ONE prompt
        # chunk with a fused decode chunk of this size (default: the
        # steady-state size, so only one n-step program ever compiles).
        # Fusion alongside prefilling slots is safe — the fused chunk's
        # garbage writes into a mid-prefill row wander pos..pos+n-1 and
        # every such position is overwritten before it becomes
        # attendable (see _gather_slot_arrays; bit-identity asserted in
        # tests).  A SMALLER value trades decode amortization for prompt
        # admission latency: each prefill chunk waits one fused chunk.
        # Without this interleave the service fell back to single ticks
        # whenever anything was prefilling, so under steady mixed
        # admit-while-decode traffic the fused path rarely engaged.
        self._prefill_decode_chunk = max(1, prefill_decode_chunk
                                         if prefill_decode_chunk is not None
                                         else self._decode_chunk)
        # Admission streams prompts in prefill_chunk-token pieces so a
        # long prompt cannot stall decoding slots for more than one
        # chunk's forward (paged storage rounds the chunk up to a page
        # multiple, see paged.py).
        self._prefill_chunk = max(1, prefill_chunk)
        self._prefill_budget = (int(prefill_budget)
                                if prefill_budget is not None
                                else 2 * self._prefill_chunk)
        if page_size is not None:
            # paged KV storage: more in-flight sequences per HBM byte
            from .paged import PagedContinuousBatcher
            self._batcher = PagedContinuousBatcher(
                params, cfg, n_slots, page_size=page_size, n_pages=n_pages,
                mesh=mesh, max_prefill_chunk=self._prefill_chunk,
                prefix_cache=prefix_cache, spec_k=self._spec_k,
                adapter_slots=adapter_slots, adapter_rank=adapter_rank,
                pp=pp, pp_microbatches=pp_microbatches)
        else:
            if prefix_cache:
                raise ValueError("prefix_cache rides the paged pool; "
                                 "pass page_size too")
            self._batcher = ContinuousBatcher(params, cfg, n_slots,
                                              mesh=mesh,
                                              spec_k=self._spec_k,
                                              adapter_slots=adapter_slots,
                                              adapter_rank=adapter_rank,
                                              pp=pp,
                                              pp_microbatches=pp_microbatches)
        if self._spec_k:
            # the REAL capability check (replaced the round-5 dense-only
            # refusal): a storage that cannot contain a k-token rejected
            # tail degrades to plain decode — counted, logged, served
            reason = self._batcher.spec_fallback_reason(self._spec_k)
            if reason is not None:
                log.warning("speculation disabled (%s): spec_k=%d on %s "
                            "storage", reason, self._spec_k,
                            self._batcher.storage_info()["kind"])
                metrics.SPEC_FALLBACK.inc(reason=reason)
                self._spec_k = 0
        # HOST-RAM SPILL TIER (paged storage only): when admission hits
        # page backpressure, the oldest-resident decoding session past
        # its TPUSHARE_SPILL_IDLE_S residency quantum exports to a
        # byte-budgeted host-RAM store (serving/migrate.py), freeing
        # its HBM pages for the admission; it faults back in — counted
        # restore latency — once the waiting queue subsides and
        # capacity frees.  Sessions ADMITTED therefore exceed what the
        # pool can hold resident (the ParvaGPU-style capacity
        # multiplier above the pool, beyond int8's in-pool 1.96x).
        # The store never silently evicts a parked session (a blob IS
        # a live client's stream): at budget, spilling refuses and the
        # victim stays resident (counted reason="spill_budget").
        self._spill = None
        self._spill_idle_s = float(_os.environ.get(
            "TPUSHARE_SPILL_IDLE_S", "0"))
        if spill_bytes:
            if not self._batcher.can_migrate():
                log.warning("spill tier disabled: storage cannot "
                            "migrate sessions (needs page_size)")
                metrics.MIGRATION_REFUSED.inc(
                    reason="unsupported_storage")
            else:
                from .migrate import HostSpillStore
                self._spill = HostSpillStore(int(spill_bytes))
        # KV-page migration plumbing (loop-owned except the command
        # list, which rides self._lock like _waiting/_cancels):
        # _mig_cmds carries export/import/deliver/reimport commands
        # from HTTP handler threads onto the loop thread; rids of
        # prefill-handoff submits park in _handoff_rids until
        # activation exports them; sessions migrated OUT keep their
        # local client's sink wired in _migrated_sinks until the peer
        # returns the finished stream (llm.py /drain migrate_to).
        self._mig_cmds: List[tuple] = []
        self._handoff_rids: set = set()
        self._migrated_sinks: Dict[int, dict] = {}
        self._resident_since: Dict[int, float] = {}
        # _lock guards ONLY the _waiting handoff; the batcher and _sinks
        # are owned by the loop thread, so decode ticks run without the
        # lock and submit() never waits on a model forward.
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._halt = threading.Event()
        self._waiting: List[Tuple] = []   # (prompt, max_new, temp, seed, eos, top_k, top_p, stream, sink, on_complete, t_submit, handoff, adapter)
        # rid -> [t_submit, prompt_len, t_first_token|None]: feeds the
        # request-latency / TTFT / per-token histograms (loop-owned,
        # like _sinks)
        self._req_meta: Dict[int, list] = {}
        # cancel(sink) handoff: the loop drains this each iteration and
        # releases the matching request wherever it is (waiting queue,
        # prefilling, decoding, or completed-but-undelivered)
        self._cancels: List[object] = []
        self._sinks: Dict[int, "object"] = {}   # loop-confined (manifest)
        # streaming requests: rid -> [sink, tokens_already_pushed,
        # on_complete].  Deltas are pushed after every loop iteration;
        # the terminal item is ("done", full_output) or
        # ("aborted", None) on shutdown.  on_complete (or None) fires on
        # the LOOP thread when the batcher finishes the request — stats
        # accounting lives there, not in the consumer, so an abandoned
        # stream still counts (see llm.py /generate_stream).
        self._stream_sinks: Dict[int, list] = {}   # loop-confined (manifest)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpushare-continuous")

    def start(self) -> "ContinuousService":
        if self._policy_pacer is not None:
            health.MONITOR.install_policy(self._policy_pacer)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._halt.set()
        self._work.set()
        if self._thread.ident is not None:   # never-started is a no-op
            self._thread.join(timeout=10)
        if self._policy_pacer is not None:
            # disarm exactly our pacer (idempotent against a successor
            # service having installed its own)
            health.MONITOR.uninstall_policy(self._policy_pacer)
        # Sentinel BOTH queued and in-flight requests — a stranded sink
        # would block its client until its own timeout. put_nowait only:
        # blocking on a full maxsize-1 sink could deadlock stop().
        with self._lock:
            waiting, self._waiting = self._waiting, []
        for item in waiting:
            stream, sink = item[7], item[8]
            try:
                sink.put_nowait(("aborted", None) if stream else None)
            except self._q.Full:
                pass
        if self._thread.is_alive():
            # Worker outlived the join (e.g. stuck in a long XLA compile
            # inside tick). _sinks is loop-owned — mutating it here would
            # race the still-running loop; leave in-flight requests to
            # their own client timeouts.
            log.warning(
                "continuous-service worker did not exit within 10s; "
                "leaving %d in-flight sink(s) to client timeouts",
                len(self._sinks))
            return
        for sink in self._sinks.values():
            try:
                sink.put_nowait(None)
            except self._q.Full:
                pass
        self._sinks.clear()
        for entry in self._stream_sinks.values():
            entry[0].put_nowait(("aborted", None))
        self._stream_sinks.clear()
        # sessions migrated out still awaiting the peer's result: their
        # clients must not block past shutdown either
        for entry in self._migrated_sinks.values():
            sink = entry.get("sink")
            if sink is None:
                continue
            try:
                sink.put_nowait(("aborted", None) if entry.get("stream")
                                else None)
            except self._q.Full:
                pass
        self._migrated_sinks.clear()

    # -- thread-safe read-only views (any thread) ----------------------
    def can_migrate(self) -> bool:
        """Whether the underlying storage supports session migration —
        the public face of the batcher capability, callable from any
        thread (HTTP handlers must not reach through ``_batcher``; the
        confinement lint enforces it)."""
        return self._batcher.can_migrate()

    def adapter_pressure(self, adapter: Optional[str]) -> bool:
        """Read-only adapter-pool pressure verdict (the llm server's
        503 admission gate) — a point-in-time snapshot, safe from
        handler threads like :meth:`storage_info`."""
        return self._batcher.adapter_pressure(adapter)

    def validate_adapter(self, adapter: Optional[str]) -> None:
        """Pure adapter validation (raises for requests this service
        could never serve) — callable from any thread."""
        self._batcher.validate_adapter(adapter)

    def storage_info(self) -> dict:
        """The storage economics dict of the underlying pool (pure
        derivation from construction-time config — safe off-loop)."""
        return self._batcher.storage_info()

    @property
    def mesh(self):
        """The serving mesh (or None) — construction-time constant."""
        return self._batcher.mesh

    def submit_stream(self, prompt: List[int], max_new_tokens: int,
                      temperature: float = 0.0, seed: int = 0,
                      eos_id: Optional[int] = None,
                      top_k: int = 0, top_p: float = 1.0,
                      on_complete=None,
                      adapter: Optional[str] = None,
                      trace: Optional[str] = None):
        """Streaming submit: the returned queue yields ``("delta",
        [new generated tokens])`` items as decoding progresses (chunk
        granularity under fused decode), then ``("done", full_output)``
        — or ``("aborted", None)`` on shutdown.  Same admission
        contract and exact same token streams as :meth:`submit`.

        ``on_complete(full_output)`` (optional) fires on the service
        loop thread when the batcher FINISHES the request — before the
        "done" item is consumed, and regardless of whether the stream
        consumer is still there.  Keep it cheap (it runs inside the
        decode loop); exceptions are swallowed with a log line."""
        return self._submit(prompt, max_new_tokens, temperature, seed,
                            eos_id, top_k, top_p, stream=True,
                            on_complete=on_complete, adapter=adapter,
                            trace=trace)

    def submit(self, prompt: List[int], max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               eos_id: Optional[int] = None,
               top_k: int = 0, top_p: float = 1.0,
               adapter: Optional[str] = None,
               trace: Optional[str] = None):
        """Returns a queue that yields the full token list (or None on
        shutdown). Raises ValueError for invalid requests (including
        ones the batcher's storage could never hold).  ``eos_id``
        finishes the request early, releasing its slot; ``top_k``/
        ``top_p`` filter the sampling distribution per request;
        ``adapter`` names the request's LoRA adapter (adapter pool
        required — ``adapter_slots``); ``trace`` is the propagated
        fleet trace id (opaque — the wire format lives in
        telemetry.propagation)."""
        return self._submit(prompt, max_new_tokens, temperature, seed,
                            eos_id, top_k, top_p, stream=False,
                            adapter=adapter, trace=trace)

    def submit_handoff(self, prompt: List[int], max_new_tokens: int,
                       temperature: float = 0.0, seed: int = 0,
                       eos_id: Optional[int] = None,
                       top_k: int = 0, top_p: float = 1.0,
                       adapter: Optional[str] = None,
                       trace: Optional[str] = None):
        """PREFILL-ONLY submit (the disaggregation sender half): the
        request prefills normally, and at the activation boundary —
        prompt in cache, first token sampled, before it joins any
        decode round — the session exports and the returned queue
        yields ``("handoff", blob)`` instead of tokens.  A request
        that COMPLETES at activation (max_new 1, instant eos) yields
        its final token list like a plain submit: there is nothing
        left to hand off.  Requires paged storage."""
        if not self._batcher.can_migrate():
            raise ValueError("prefill handoff requires paged storage "
                             "(pass page_size)")
        return self._submit(prompt, max_new_tokens, temperature, seed,
                            eos_id, top_k, top_p, stream=False,
                            handoff=True, adapter=adapter, trace=trace)

    def import_session(self, blob: bytes):
        """Schedule a migration blob for import on the loop thread;
        returns a queue yielding the session's FINAL token list at
        completion (exactly like :meth:`submit`), or ``("refused",
        reason)`` when the pool cannot take it (reasons enumerate
        :data:`tpushare.serving.migrate.MIGRATION_REFUSAL_REASONS`),
        or None on shutdown."""
        sink = self._q.Queue(maxsize=1)
        with self._lock:
            self._mig_cmds.append(("import", blob, sink))
        self._work.set()
        return sink

    def migrate_out(self, timeout: float = 30.0):
        """Export ONE decoding session off the pool (loop thread does
        the work): returns ``(rid, blob)`` — the session's slot and
        pages are FREED, its client's sink stays wired awaiting
        :meth:`deliver_migrated` / :meth:`reimport` — or None when
        nothing is migratable.  The /drain ``migrate_to`` sender
        half."""
        q = self._q.Queue(maxsize=1)
        with self._lock:
            self._mig_cmds.append(("export", q))
        self._work.set()
        try:
            return q.get(timeout=timeout)
        except self._q.Empty:
            return None

    def deliver_migrated(self, rid: int, tokens: List[int]) -> None:
        """The peer finished migrated-out session ``rid``: route its
        final token list to the local client's still-wired sink."""
        with self._lock:
            self._mig_cmds.append(("deliver", rid, tokens))
        self._work.set()

    def reimport(self, rid: int, blob: bytes) -> None:
        """The peer refused migrated-out session ``rid``: scatter the
        blob back into the local pool and resume serving it here (its
        sink wiring is restored; retried with backoff until capacity
        frees — the session's pages were just released, so it fits
        once in-flight admissions settle)."""
        with self._lock:
            self._mig_cmds.append(("reimport", rid, blob, 0))
        self._work.set()

    def _submit(self, prompt, max_new_tokens, temperature, seed, eos_id,
                top_k, top_p, stream: bool, on_complete=None,
                handoff: bool = False, adapter: Optional[str] = None,
                trace: Optional[str] = None):
        self._batcher.validate_request(prompt, max_new_tokens)
        self._batcher.validate_sampling(top_k, top_p)
        self._batcher.validate_adapter(adapter)
        if self._spec_k:
            # storage-aware: only the full-size dense pool still needs
            # the +k cache headroom (see validate_spec_request)
            self._batcher.validate_spec_request(
                len(prompt), max_new_tokens, self._spec_k)
        # streaming sinks are unbounded (many deltas); final-only sinks
        # hold exactly one item
        sink = self._q.Queue() if stream else self._q.Queue(maxsize=1)
        metrics.REQUESTS.inc()
        with self._lock:
            self._waiting.append(
                (prompt, max_new_tokens, temperature, seed, eos_id,
                 top_k, top_p, stream, sink, on_complete,
                 time.perf_counter(), handoff, adapter, trace))
        self._work.set()
        return sink

    def cancel(self, sink) -> None:
        """Abandon the request behind ``sink`` (the queue a submit
        returned): if still waiting it is dropped; if admitted, its
        slot and storage are released on the loop's next iteration
        (≤ one decode chunk away).  Callable from any thread; idempotent
        and a no-op for already-delivered requests.  The sink receives
        no further items — the canceller, by definition, is not
        listening."""
        with self._lock:
            self._cancels.append(sink)
        self._work.set()

    def _drain_cancels(self) -> None:
        """Loop-thread half of :meth:`cancel`."""
        with self._lock:
            cancels, self._cancels = self._cancels, []
            for sink in cancels:
                self._waiting = [item for item in self._waiting
                                 if item[8] is not sink]
        for sink in cancels:
            for rid, entry in list(self._stream_sinks.items()):
                if entry[0] is sink:
                    self._batcher.cancel(rid)
                    del self._stream_sinks[rid]
                    self._req_meta.pop(rid, None)
                    self._forget_session(rid)
                    break
            else:
                for rid, s in list(self._sinks.items()):
                    if s is sink:
                        self._batcher.cancel(rid)
                        del self._sinks[rid]
                        self._req_meta.pop(rid, None)
                        self._forget_session(rid)
                        break

    def _forget_session(self, rid: int) -> None:
        """Drop a cancelled request's migration-plane state: a SPILLED
        session's blob (its slot/pages were never re-acquired, so the
        batcher-side cancel found nothing) and any pending handoff/
        residency bookkeeping."""
        self._resident_since.pop(rid, None)
        self._handoff_rids.discard(rid)
        if self._spill is not None and self._spill.take(rid) is not None:
            metrics.CANCELLATIONS.inc()
            self._observe_spill()

    # -- KV-page migration: loop-thread halves -------------------------
    def _observe_spill(self) -> None:
        if self._spill is not None:
            metrics.SPILL_BYTES.set(self._spill.bytes_used)
            metrics.SPILL_SESSIONS.set(len(self._spill))

    def _abort_rid(self, rid: int) -> None:
        """Terminal failure for an in-flight request: sentinel its sink
        the way stop() would (None / ("aborted", None))."""
        self._req_meta.pop(rid, None)
        self._handoff_rids.discard(rid)
        sink = self._sinks.pop(rid, None)
        if sink is not None:
            try:
                sink.put_nowait(None)
            except self._q.Full:
                pass
            return
        entry = self._stream_sinks.pop(rid, None)
        if entry is not None:
            entry[0].put(("aborted", None))

    def _spill_one(self) -> bool:
        """Export the longest-resident decoding session past its
        residency quantum into the host-RAM store, freeing its slot
        and pages.  False when nothing is eligible or the store's byte
        budget refuses (the victim then stays resident — counted)."""
        if self._spill is None:
            return False
        now = time.monotonic()
        cands = sorted(
            (self._resident_since.get(s.request_id, 0.0), s.request_id)
            for s in self._batcher.slots.values()
            if s.request_id not in self._handoff_rids)
        for since, rid in cands:
            if now - since < self._spill_idle_s:
                break       # longest-resident is still in quantum
            blob = self._batcher.export_session(rid)
            if not self._spill.put(rid, blob):
                metrics.MIGRATION_REFUSED.inc(reason="spill_budget")
                return False
            self._batcher.pop_session(rid)
            self._resident_since.pop(rid, None)
            metrics.MIGRATIONS_OUT.inc(kind="spill")
            RECORDER.record("session_spilled", rid=rid,
                            bytes=len(blob))
            self._observe_spill()
            return True
        return False

    def _restore_spilled(self) -> None:
        """Fault parked sessions back into the pool, oldest first —
        only while the waiting queue is empty (new admissions keep
        FIFO priority over re-residency; a restored session would
        otherwise be re-spilled before decoding a token, starving it
        behind a long queue)."""
        if self._spill is None or not len(self._spill):
            return
        with self._lock:
            if self._waiting:
                return
        while self._batcher.free_slots():
            rid = self._spill.oldest()
            if rid is None:
                return
            blob = self._spill.take(rid)
            t0 = time.perf_counter()
            try:
                got = self._batcher.import_session(blob, rid=rid)
            except Exception:
                log.exception("restoring spilled session %d failed; "
                              "aborting it", rid)
                self._abort_rid(rid)
                self._observe_spill()
                continue
            if got is None:
                # pages still short: back to the FRONT (it keeps its
                # restore priority), retry when capacity frees
                self._spill.put(rid, blob, front=True)
                return
            metrics.SPILL_RESTORE.observe(time.perf_counter() - t0)
            metrics.MIGRATIONS_IN.inc(kind="restore")
            RECORDER.record("session_restored", rid=rid)
            self._resident_since[rid] = time.monotonic()
            self._observe_spill()

    def _sweep_handoffs(self) -> None:
        """Export prefill-handoff submits the moment they ACTIVATE:
        the slot releases and the client's sink yields ("handoff",
        blob) — the disaggregation boundary.  Requests that completed
        at activation deliver tokens through the normal drain."""
        if not self._handoff_rids:
            return
        b = self._batcher
        by_rid = {s.request_id: i for i, s in b.slots.items()}
        for rid in list(self._handoff_rids):
            if rid in b.completed:
                self._handoff_rids.discard(rid)   # nothing to hand off
                continue
            if rid not in by_rid:
                continue                          # still prefilling
            self._handoff_rids.discard(rid)
            blob = b.export_session(rid)
            b.pop_session(rid)
            self._resident_since.pop(rid, None)
            metrics.MIGRATIONS_OUT.inc(kind="handoff")
            self._req_meta.pop(rid, None)
            sink = self._sinks.pop(rid, None)
            if sink is not None:
                sink.put(("handoff", blob))

    def _drain_migrations(self) -> None:
        """Loop-thread half of the migration command queue."""
        with self._lock:
            if not self._mig_cmds:
                return
            cmds, self._mig_cmds = self._mig_cmds, []
        retry = []
        for cmd in cmds:
            try:
                if cmd[0] == "export":
                    self._mig_export(cmd[1])
                elif cmd[0] == "import":
                    self._mig_import(cmd[1], cmd[2])
                elif cmd[0] == "deliver":
                    self._mig_deliver(cmd[1], cmd[2])
                elif cmd[0] == "reimport":
                    if not self._mig_reimport(cmd[1], cmd[2]):
                        if cmd[3] >= 10_000:
                            log.error("reimport of session %d starved; "
                                      "aborting it", cmd[1])
                            self._migrated_sinks.pop(cmd[1], None)
                            self._abort_rid(cmd[1])
                        else:
                            retry.append(("reimport", cmd[1], cmd[2],
                                          cmd[3] + 1))
            except Exception:
                # one poisoned command must NEVER kill the serving loop
                # (every request on the replica would hang); the
                # command's own handlers already map the expected
                # failures to counted refusals — this is the backstop
                log.exception("migration command %r failed; dropped",
                              cmd[0])
        if retry:
            with self._lock:
                self._mig_cmds.extend(retry)

    def _mig_export(self, reply) -> None:
        b = self._batcher
        rid = None
        if b.can_migrate():
            for s in b.slots.values():
                if s.request_id not in self._handoff_rids:
                    rid = s.request_id
                    break
        if rid is None:
            reply.put(None)
            return
        blob = b.export_session(rid)
        b.pop_session(rid)
        self._resident_since.pop(rid, None)
        # the local client's sink stays wired: the peer's finished
        # stream (deliver_migrated) or a reimport routes back to it
        sink = self._sinks.pop(rid, None)
        if sink is not None:
            self._migrated_sinks[rid] = {"stream": False, "sink": sink}
        else:
            se = self._stream_sinks.pop(rid, None)
            self._migrated_sinks[rid] = (
                {"stream": True, "sink": se[0], "pushed": se[1],
                 "on_complete": se[2]} if se is not None
                else {"stream": False, "sink": None})
        metrics.MIGRATIONS_OUT.inc(kind="drain")
        RECORDER.record("session_migrated_out", rid=rid,
                        bytes=len(blob))
        reply.put((rid, blob))

    def _mig_import(self, blob, sink) -> None:
        from . import migrate
        b = self._batcher

        def refuse(reason):
            metrics.MIGRATION_REFUSED.inc(reason=reason)
            RECORDER.record("migration_refused", reason=reason)
            sink.put(("refused", reason))

        if not b.can_migrate():
            refuse("unsupported_storage")
            return
        try:
            rid = b.import_session(blob)
            # capacity backpressure: the spill tier (when on) makes
            # room the same way admission does
            while rid is None and self._spill_one():
                rid = b.import_session(blob)
        except migrate.ConfigMismatch:
            refuse("config_mismatch")
            return
        except migrate.BlobError:
            refuse("bad_blob")
            return
        if rid is None:
            refuse("pool_full")
            return
        slot = next(s for s in b.slots.values() if s.request_id == rid)
        self._req_meta[rid] = [time.perf_counter(), slot.prompt_len,
                               None]
        self._sinks[rid] = sink
        self._resident_since[rid] = time.monotonic()
        metrics.MIGRATIONS_IN.inc(kind="import")
        RECORDER.record("session_migrated_in", rid=rid,
                        bytes=len(blob))

    def _mig_deliver(self, rid: int, tokens: List[int]) -> None:
        entry = self._migrated_sinks.pop(rid, None)
        if entry is None:
            return
        self._observe_request(rid, len(tokens))
        if entry.get("stream"):
            pushed = entry.get("pushed", 0)
            if len(tokens) > pushed:
                entry["sink"].put(("delta", tokens[pushed:]))
            cb = entry.get("on_complete")
            if cb is not None:
                try:
                    cb(tokens)
                except Exception:
                    log.exception("migrated on_complete raised; "
                                  "continuing")
            entry["sink"].put(("done", tokens))
        elif entry["sink"] is not None:
            entry["sink"].put(tokens)

    def _mig_reimport(self, rid: int, blob) -> bool:
        try:
            got = self._batcher.import_session(blob, rid=rid)
        except Exception:
            log.exception("reimport of session %d failed; aborting it",
                          rid)
            self._migrated_sinks.pop(rid, None)
            self._abort_rid(rid)
            return True
        if got is None:
            return False
        entry = self._migrated_sinks.pop(rid, None)
        if entry is not None:
            if entry.get("stream"):
                self._stream_sinks[rid] = [
                    entry["sink"], entry.get("pushed", 0),
                    entry.get("on_complete")]
            elif entry["sink"] is not None:
                self._sinks[rid] = entry["sink"]
        self._resident_since[rid] = time.monotonic()
        metrics.MIGRATIONS_IN.inc(kind="import")
        return True

    def _observe_request(self, rid: int, out_len: int) -> None:
        """Feed the request-level histograms at completion (loop thread).

        Streaming requests recorded TTFT at their first delta, so their
        per-token time covers the decode tail; one-shot requests deliver
        everything at once, so TTFT is the full latency and per-token
        time spreads it over the generated tokens.
        """
        self._resident_since.pop(rid, None)   # migration bookkeeping
        meta = self._req_meta.pop(rid, None)
        if meta is None:
            return
        now = time.perf_counter()
        t_sub, prompt_len, t_first = meta
        total = now - t_sub
        metrics.REQUEST_LATENCY.observe(total)
        n_gen = max(1, out_len - prompt_len)
        if t_first is not None:
            if n_gen > 1:
                metrics.TPOT.observe((now - t_first) / (n_gen - 1))
        else:
            metrics.TTFT.observe(total)
            metrics.TPOT.observe(total / n_gen)

    def snapshot(self) -> dict:
        """Occupancy for observability: {slots, active, prefilling,
        queued}.

        active/queued are read without the loop's cadence in mind — a
        point-in-time view for /stats, not a synchronization primitive.
        """
        with self._lock:
            queued = len(self._waiting)
        snap = {"slots": self._batcher.n_slots,
                "active": len(self._batcher.slots),
                "prefilling": len(self._batcher.prefilling),
                "queued": queued}
        if self._spill is not None:
            snap["spilled"] = len(self._spill)
            snap["spill_bytes"] = self._spill.bytes_used
        adapters = self._batcher.adapter_info()
        if adapters is not None:
            snap["adapters"] = adapters
        if self._spec_k:
            st = dict(self._batcher._spec_stats)
            st["tokens_per_round"] = (round(st["tokens"] / st["rounds"], 3)
                                      if st["rounds"] else None)
            snap["speculation"] = st
        if self._policy_pacer is not None:
            snap["policy"] = self._policy_pacer.snapshot()
        return snap

    def _spec_route(self) -> bool:
        """Speculate this round?  Greedy-only routing stays: spec rows
        exist only for greedy slots — with none active, a spec round
        would be a fused decode chunk dragging k dead lanes per row, so
        the loop falls back to the plain path and counts the skipped
        opportunity (``tpushare_spec_fallback_total{reason=
        sampling_only}``).  Sampling slots alongside at least one
        greedy slot RIDE the spec program as decode rows instead of
        blocking it (the round-5 all-greedy gate is gone)."""
        slots = self._batcher.slots
        if not slots:
            return False
        if any(s.temperature == 0.0 for s in slots.values()):
            return True
        metrics.SPEC_FALLBACK.inc(reason="sampling_only")
        return False

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._halt.is_set():
            if not self._work.wait(timeout=0.5):
                continue   # stay asleep while idle; submit() re-sets it
            self._drain_cancels()
            self._drain_migrations()
            self._restore_spilled()
            # Take the waiting handoff under the lock, then decode without
            # it — admission and ticks only touch loop-owned state.
            while True:
                with self._lock:
                    if not self._waiting:
                        break
                    item = self._waiting.pop(0)
                (prompt, max_new, temp, seed, eos_id, tk, tp, stream,
                 sink, on_cb, t_sub, handoff, adapter, trace) = item
                rid = None
                admit_failed = False
                while True:
                    if self._batcher.free_slots():
                        try:
                            rid = self._batcher.admit_chunked(
                                prompt, max_new, temperature=temp,
                                seed=seed, chunk=self._prefill_chunk,
                                eos_id=eos_id, top_k=tk, top_p=tp,
                                adapter=adapter, trace=trace)
                        except Exception:
                            # a per-request admission failure (e.g. an
                            # adapter LOADER error for a bad name) must
                            # abort THAT request, never the loop every
                            # tenant's serving rides on
                            log.exception(
                                "admission failed for a queued request"
                                " (adapter=%r); aborting it", adapter)
                            admit_failed = True
                            break
                        if rid is not None:
                            break
                    # Backpressure (no slot, or paged storage out of
                    # pages): the SPILL TIER parks the longest-resident
                    # decoding session in host RAM and retries — the
                    # capacity multiplier.  Bounded: each pass removes
                    # one resident session.  ADAPTER-pool pressure only
                    # spills while some decoding session holds a pin
                    # (exporting it releases the pin; spilling
                    # base-model sessions frees pages this refusal
                    # does not need).
                    if (adapter is not None
                            and self._batcher.adapter_pressure(adapter)
                            and not
                            self._batcher.adapter_spill_can_help()):
                        break
                    if not self._spill_one():
                        break
                if admit_failed:
                    try:
                        sink.put_nowait(("aborted", None) if stream
                                        else None)
                    except self._q.Full:
                        pass
                    continue
                if rid is None:
                    # No spill capacity either: requeue at the FRONT
                    # and stop admitting until a tick releases capacity
                    # — dropping here would strand the sink.
                    with self._lock:
                        self._waiting.insert(0, item)
                    break
                # queue wait ends at ADMISSION (a slot + storage granted;
                # prefill compute starts next round) — the backpressure
                # half of TTFT, separated from prompt compute
                metrics.REQUEST_QUEUE.observe(time.perf_counter() - t_sub)
                # chunked admission never completes at admit time (even a
                # 1-token request finishes in advance_prefill); results
                # are delivered by the post-tick completed drain below
                self._req_meta[rid] = [t_sub, len(prompt), None]
                self._resident_since[rid] = time.monotonic()
                if handoff:
                    self._handoff_rids.add(rid)
                if stream:
                    self._stream_sinks[rid] = [sink, len(prompt), on_cb]
                else:
                    self._sinks[rid] = sink
            spec = bool(self._spec_k) and self._spec_route()
            if self._batcher.prefilling:
                if self._mixed_step and spec:
                    # ONE dispatch per round, speculation co-resident:
                    # the coalesced prefill block fused with the spec
                    # verify rounds (greedy slots speculate, sampling
                    # slots ride as decode rows — see tick_mixed_spec).
                    active = self._batcher.tick_mixed_spec(
                        self._spec_rounds,
                        chunk=self._prefill_chunk,
                        budget=self._prefill_budget,
                        k=self._spec_k, ngram=self._spec_ngram)
                elif self._mixed_step:
                    # ONE dispatch per round: all pending prompt chunks
                    # under the token budget, coalesced and fused with
                    # the decode scan (see tick_mixed).
                    active = self._batcher.tick_mixed(
                        self._prefill_decode_chunk,
                        chunk=self._prefill_chunk,
                        budget=self._prefill_budget)
                else:
                    # Sequential reference policy: one prompt chunk per
                    # prefilling slot, then a fused decode chunk (see
                    # __init__ on _prefill_decode_chunk).
                    self._batcher.advance_prefill()
                    if self._prefill_decode_chunk > 1:
                        active = self._batcher.tick_fused(
                            self._prefill_decode_chunk)
                    else:
                        active = self._batcher.tick()
            elif spec:
                # steady state with greedy slots active: speculative
                # rounds (greedy-exact, so interleaving with the fused
                # path below stays safe as traffic mixes shift)
                active = self._batcher.tick_spec(
                    self._spec_rounds, k=self._spec_k,
                    ngram=self._spec_ngram)
            elif self._decode_chunk > 1:
                active = self._batcher.tick_fused(self._decode_chunk)
            else:
                active = self._batcher.tick()
            # prefill-handoff submits export the moment they activate
            # (BEFORE stream/completed delivery: a handed-off session
            # must never also deliver tokens locally)
            self._sweep_handoffs()
            # streaming deltas: push whatever each live streaming slot
            # grew this iteration (the loop thread owns slot outputs)
            if self._stream_sinks:
                by_rid = {s.request_id: s
                          for s in self._batcher.slots.values()}
                for rid, entry in list(self._stream_sinks.items()):
                    sink, pushed = entry[0], entry[1]
                    out = None
                    s = by_rid.get(rid)
                    if s is not None:
                        out = s.output
                    elif rid in self._batcher.completed:
                        out = self._batcher.completed[rid]
                    if out is not None and len(out) > pushed:
                        meta = self._req_meta.get(rid)
                        if meta is not None and meta[2] is None:
                            meta[2] = time.perf_counter()
                            metrics.TTFT.observe(meta[2] - meta[0])
                        sink.put(("delta", out[pushed:]))
                        entry[1] = len(out)
            for rid in list(self._batcher.completed):
                sink = self._sinks.pop(rid, None)
                if sink is not None:
                    out = self._batcher.completed.pop(rid)
                    self._observe_request(rid, len(out))
                    sink.put(out)
                    continue
                entry = self._stream_sinks.pop(rid, None)
                if entry is not None:
                    out = self._batcher.completed.pop(rid)
                    self._observe_request(rid, len(out))
                    if entry[2] is not None:
                        try:
                            entry[2](out)
                        except Exception:
                            log.exception("stream on_complete callback "
                                          "raised; continuing")
                    entry[0].put(("done", out))
            idle = False
            with self._lock:
                queued = len(self._waiting)
                if (not active and not self._batcher.prefilling
                        and not queued and not self._sinks
                        and not self._stream_sinks
                        and not self._mig_cmds
                        and not self._migrated_sinks
                        and not (self._spill is not None
                                 and len(self._spill))):
                    self._work.clear()
                    idle = True
            if idle:
                # going idle: push whatever the DERIVED_OBSERVE_EVERY
                # cadence has not flushed yet — a burst shorter than
                # 16 rounds must still show up in the work counters
                # (outside the lock; flush touches only loop-owned
                # accumulators + the registry's own locks)
                self._batcher.flush_cost()
            # backpressure visibility: requests submitted but not yet
            # admitted to a slot — the DEMAND signal the tenant-policy
            # slack reallocation reads (a tenant with queued work is
            # under-using involuntarily and donates nothing; see
            # serving/policy.py effective_entitlements)
            metrics.REQUEST_QUEUE_DEPTH.set(queued)
        # loop exit (stop / drain-to-halt): flush whatever the cadence
        # left behind — still on the loop thread, so the accumulators
        # are ours to drain
        self._batcher.flush_cost()
