"""Minimal batched inference engine + QPS measurement.

This is the workload that runs *inside* an allocated container for the
co-location benchmarks (BASELINE configs 2–4): a jitted forward, a
background micro-batcher that coalesces concurrent requests (padding to a
fixed batch so the jit cache stays warm), and a throughput probe.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..telemetry import health
from . import metrics


class InferenceEngine:
    """Wraps a jitted ``fn(batch_tokens) -> outputs`` with micro-batching.

    ``pass_mask=True`` calls ``fn(tokens, mask)`` with a [B, S] validity
    mask instead — REQUIRED for encoder models when ragged requests are
    padded to the fixed shape, or pad positions bleed into real outputs
    through bidirectional attention.
    """

    def __init__(self, fn: Callable, batch_size: int, seq_len: int,
                 max_wait_ms: float = 2.0, pad_id: int = 0,
                 pass_mask: bool = False, pipeline_depth: int = 2):
        self.fn = jax.jit(fn)
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.max_wait = max_wait_ms / 1000.0
        self.pad_id = pad_id
        self.pass_mask = pass_mask
        # Server-loop dispatch pipelining: up to this many batches ride
        # the device queue before the oldest is fetched and delivered —
        # the same dispatch-latency hiding measure_qps documents, for
        # REAL request traffic (a blocking per-batch loop pays the full
        # host<->device round trip per batch; ~70 ms on a tunnel-attached
        # chip).  Depth bounds per-request latency at ~depth x batch
        # time; 1 restores strictly serial behavior.
        self.pipeline_depth = max(1, pipeline_depth)
        # (tokens, result queue, submit time, request id) — the submit
        # timestamp rides with the request so deliver can observe the
        # true submit->deliver latency (TTFT for this one-shot engine);
        # the request id threads submit -> batch -> dispatch -> deliver
        # so dispatch-guard flight events and trace spans can name the
        # requests in flight (a stalled dispatch is traceable to them)
        self._q: "queue.Queue[Tuple[np.ndarray, queue.Queue, float, int]]" \
            = queue.Queue()
        # itertools.count: submit() is multi-producer (HTTP handler
        # threads), and a duplicated rid would make the flight
        # recorder's stall forensics name the wrong request
        self._next_rid = itertools.count(1)
        # dispatched-but-undelivered batches; loop-owned in normal
        # operation, but engine-level so stop() can sentinel these
        # clients if the worker wedges in a device fetch (a tunnel
        # outage can hang np.asarray for ~25 min)
        self._inflight: "collections.deque" = collections.deque()
        self._halt = threading.Event()
        self._worker: Optional[threading.Thread] = None

    # -- sync one-shot ------------------------------------------------------
    def infer(self, tokens: np.ndarray, mask: Optional[np.ndarray] = None):
        """tokens [B, S] -> outputs, blocking.

        The barrier is a host fetch of one scalar from the result, not
        ``block_until_ready`` — which has returned early on the remote
        axon backend (CLAUDE.md); executions are in-order per device,
        so one fetch drains the stream (lint: no-block-until-ready)."""
        out = self.infer_async(tokens, mask)
        leaf = jax.tree_util.tree_leaves(out)[0]
        # first-element index, not reshape(-1): reshape would be a
        # second device dispatch (~70ms RPC on the tunnel) per infer
        float(leaf[(0,) * leaf.ndim])
        return out

    def infer_async(self, tokens: np.ndarray,
                    mask: Optional[np.ndarray] = None):
        """tokens [B, S] -> outputs WITHOUT blocking: jax's async dispatch
        queues the forward on the device and returns immediately.  A
        throughput driver keeps several batches in flight so each pays
        compute time, not a host<->device round trip (the tunnel-attached
        chip has multi-ms dispatch latency that would otherwise dominate
        sub-10ms forwards)."""
        metrics.BATCHES.inc()
        # observe=False: dispatch is async (near-zero wall) — device
        # time is attributed at the fetch; the guard exists because a
        # dispatch that BLOCKS (tracing/compiling against a dead
        # backend) must still trip the stall watchdog
        with health.MONITOR.dispatch_guard("prefill", observe=False):
            if self.pass_mask:
                if mask is None:
                    mask = np.ones_like(tokens, dtype=np.int32)
                return self.fn(jnp.asarray(tokens), jnp.asarray(mask))
            return self.fn(jnp.asarray(tokens))

    def warmup(self):
        dummy = np.zeros((self.batch_size, self.seq_len), dtype=np.int32)
        self.infer(dummy)

    # -- server-style batching ---------------------------------------------
    def start(self):
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="tpushare-batcher")
        self._worker.start()
        return self

    def stop(self):
        self._halt.set()
        if self._worker is not None:
            self._worker.join(timeout=5)
        if self._worker is not None and self._worker.is_alive():
            # Worker wedged (most likely a hung device fetch): sentinel
            # the DISPATCHED clients too — their results may never
            # arrive, and the zombie worker's late put_nowait will just
            # hit a full queue and be dropped.
            for _, b, _ in list(self._inflight):
                for _, out_q, _, _ in b:
                    try:
                        out_q.put_nowait(None)
                    except queue.Full:
                        pass
        # Deliver a sentinel to requests still queued so no client blocks
        # forever on its result queue.
        while True:
            try:
                _, out_q, _, _ = self._q.get_nowait()
            except queue.Empty:
                break
            out_q.put(None)

    def submit(self, tokens: np.ndarray) -> queue.Queue:
        """Enqueue one request [S]; returns a queue delivering the result."""
        out: queue.Queue = queue.Queue(maxsize=1)
        metrics.REQUESTS.inc()
        self._q.put((tokens, out, time.perf_counter(),
                     next(self._next_rid)))
        return out

    def _loop(self):
        inflight = self._inflight

        def deliver_oldest():
            outputs, b, rids = inflight.popleft()
            # host fetch, not block_until_ready (unreliable on remote
            # backends): executions are in-order per device, so pulling
            # this batch's outputs drains everything dispatched before
            # the stall-watchdog guard brackets the fetch (the one call
            # that hangs on a dead tunnel) and attributes device time:
            # an encoder forward is a full-context pass, phase=prefill
            with health.MONITOR.dispatch_guard("prefill",
                                               requests=len(b),
                                               rids=rids) as g, \
                    telemetry.span("engine.deliver", cat="serving",
                                   requests=len(b), rids=rids):
                host = np.asarray(outputs)
            now = time.perf_counter()
            if g.device_s is not None and b:
                # per-request attribution: this dispatch's measured
                # device residency split equally over the requests that
                # rode it (one-shot inference is all prefill); one
                # batched observe — single lock on the hot path
                metrics.REQUEST_DEVICE_TIME.observe_n(
                    g.device_s / len(b), len(b), phase="prefill")
            lats, tpots = [], []
            for i, (toks, out_q, t_sub, _) in enumerate(b):
                dt = now - t_sub
                lats.append(dt)
                tpots.append(dt / max(1, min(len(toks), self.seq_len)))
                try:
                    # put_nowait: if stop() already sentineled this
                    # client (hung-fetch recovery), don't wedge the
                    # worker on its full maxsize-1 queue
                    out_q.put_nowait(host[i])
                except queue.Full:
                    pass
            # batched observes (one lock per family, not per request):
            # one-shot inference delivers the full result at once, so
            # TTFT == request latency and per-token time is the latency
            # spread over each request's real positions
            metrics.REQUEST_LATENCY.observe_many(lats)
            metrics.TTFT.observe_many(lats)
            metrics.TPOT.observe_many(tpots)

        while not self._halt.is_set():
            batch: List[Tuple[np.ndarray, queue.Queue, float, int]] = []
            try:
                # stay responsive while results are pending delivery
                batch.append(self._q.get(timeout=0.002 if inflight
                                         else 0.05))
            except queue.Empty:
                if inflight:
                    deliver_oldest()   # idle: drain the pipeline
                continue
            with telemetry.span("engine.batch", cat="serving"):
                deadline = time.monotonic() + self.max_wait
                while len(batch) < self.batch_size:
                    budget = deadline - time.monotonic()
                    if budget <= 0:
                        break
                    try:
                        batch.append(self._q.get(timeout=budget))
                    except queue.Empty:
                        break
                tokens = np.full((self.batch_size, self.seq_len),
                                 self.pad_id, dtype=np.int32)
                mask = np.zeros((self.batch_size, self.seq_len),
                                dtype=np.int32)
                for i, (toks, _, _, _) in enumerate(batch):
                    n = min(len(toks), self.seq_len)
                    tokens[i, :n] = toks[:n]
                    mask[i, :n] = 1
            metrics.BATCH_FILL.set(len(batch) / self.batch_size)
            rids = [rid for _, _, _, rid in batch]
            # queue wait ends when the request joins a dispatched batch
            # (the engine's admission point); the remaining latency is
            # device + delivery.  Batched observe: one lock per batch.
            t_dispatch = time.perf_counter()
            metrics.REQUEST_QUEUE.observe_many(
                [t_dispatch - t_sub for _, _, t_sub, _ in batch])
            with telemetry.span("engine.dispatch", cat="serving",
                                requests=len(batch), rids=rids):
                # infer_async carries its own stall guard
                inflight.append((self.infer_async(tokens, mask), batch,
                                 rids))
            if len(inflight) >= self.pipeline_depth:
                deliver_oldest()
        while inflight:                # halt: nothing may stay undelivered
            deliver_oldest()


def measure_qps(engine: InferenceEngine, n_batches: int = 20,
                warmup_batches: int = 3, max_in_flight: int = 8) -> dict:
    """Sustained throughput of full batches through the jitted forward.

    Batches are PIPELINED: up to ``max_in_flight`` dispatches ride the
    device queue concurrently (bounded so host memory and the device
    stream stay sane), and the clock stops when the last one completes.
    This measures compute-limited serving throughput; a blocking
    per-batch loop would instead measure dispatch round-trip latency,
    which on a tunnel-attached chip is an order of magnitude larger
    than the forward itself.  ``latency_ms`` is the sustained per-batch
    PERIOD (wall / batches), not a single-request latency.
    """
    def fetch_barrier(result):
        # block_until_ready is NOT a reliable barrier on remote backends
        # (axon: observed returning before execution).  Executions are
        # in-order per device, so host-fetching ONE element of a result
        # forces completion of everything dispatched before it (the
        # [0,...] index is computed on device; only a scalar crosses
        # the wire).  The stall guard brackets the fetch — the call
        # that hangs on a dead tunnel — and attributes the drained
        # pipeline's device time (phase=prefill: encoder forwards).
        with health.MONITOR.dispatch_guard("prefill"):
            leaf = jax.tree_util.tree_leaves(result)[0]
            float(leaf[(0,) * leaf.ndim])

    tokens = np.random.randint(
        1, 100, size=(engine.batch_size, engine.seq_len), dtype=np.int32)
    last = None
    for _ in range(warmup_batches):
        last = engine.infer_async(tokens)
    if last is not None:
        fetch_barrier(last)   # also compiles the barrier's index program
    # warmup_batches=0 is honored literally: no hidden warmup dispatch,
    # so the timed window then includes the compile — the caller asked
    # to measure cold-start, not sustained, throughput.
    in_flight: List = []
    t0 = time.perf_counter()
    for _ in range(n_batches):
        last = engine.infer_async(tokens)
        in_flight.append(last)
        if len(in_flight) >= max_in_flight:
            # fetch, not block_until_ready: in-order execution means
            # fetching entry i waits only through i, so the pipeline
            # stays full while the in-flight bound is actually enforced
            fetch_barrier(in_flight.pop(0))
    fetch_barrier(last)
    dt = time.perf_counter() - t0
    queries = n_batches * engine.batch_size
    # telemetry lands AFTER the clock stops: the timed loop itself adds
    # only the per-dispatch counter inc (the <2% overhead budget)
    metrics.QPS.set(queries / dt)
    health.refresh_device_utilization()
    telemetry.tracer.instant("engine.measure_qps", cat="serving",
                             qps=round(queries / dt, 2),
                             batches=n_batches)
    return {
        "qps": queries / dt,
        "latency_ms": dt / n_batches * 1000.0,
        "batch_size": engine.batch_size,
        "seq_len": engine.seq_len,
        "seconds": dt,
    }
