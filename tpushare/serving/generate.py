"""Autoregressive generation: prefill + jitted single-token decode.

TPU-shaped decoding: the KV cache is a fixed-capacity buffer (static
shapes; one compile for prefill, one for the decode step regardless of
generation length), greedy or temperature sampling, early-exit handled
host-side so the jitted step stays branch-free.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..models import transformer


@functools.lru_cache(maxsize=8)
def make_decode_fns(cfg: transformer.ModelConfig):
    """(prefill_fn, step_fn), jitted once per config.

    Cached per cfg (hashable frozen dataclass): a fresh jit wrapper per
    call would key a new XLA cache entry per request and recompile on
    the serving hot path.
    """

    # Caches are donated: the caller always rebinds them, and in-place
    # XLA updates avoid holding two cache copies across the decode loop.
    @functools.partial(jax.jit, static_argnames=("prompt_len",),
                       donate_argnums=(2,))
    def prefill(params, tokens, caches, prompt_len: int):
        logits, caches = transformer.forward(
            params, tokens[:, :prompt_len], cfg, kv_caches=caches,
            cache_len=0)
        return logits[:, -1], caches

    @functools.partial(jax.jit, donate_argnums=(2,))
    def step(params, token, caches, pos):
        logits, caches = transformer.forward(
            params, token[:, None], cfg, kv_caches=caches, cache_len=pos)
        return logits[:, 0], caches

    return prefill, step


@functools.lru_cache(maxsize=8)
def make_fused_decode(cfg: transformer.ModelConfig):
    """Multi-token decode: ONE jitted call scans ``n`` steps on device
    (token -> forward -> argmax-or-sample -> next token) and returns all
    generated tokens.

    One host round trip per ``n`` tokens instead of per token — the
    difference between ~14 tokens/s (per-dispatch, ~70 ms RPC each on a
    tunnel-attached chip) and compute-limited decode.  Sampling carries
    the PRNG key through the scan with the SAME split-per-step sequence
    :func:`generate`'s host loop performs, so the two paths produce
    bit-identical streams (PRNG splits are deterministic functions).
    """

    # Compile count must stay bounded on the serving hot path: ``n`` is
    # BUCKETED by the caller (powers of two) and ``temperature`` is a
    # TRACED operand — only the sample/greedy choice is static.  A raw
    # client float as a static arg would recompile the whole n-step scan
    # per distinct value (~20-140 s each on a tunneled backend).
    @functools.partial(jax.jit, static_argnames=("n", "sample"),
                       donate_argnums=(2,))
    def decode_n(params, token0, caches, pos0, key, temperature, n: int,
                 sample: bool):
        def body(carry, _):
            token, caches, pos, key = carry
            logits, caches = transformer.forward(
                params, token[:, None], cfg, kv_caches=caches,
                cache_len=pos)
            if sample:
                key, sub = jax.random.split(key)
                nxt = jax.random.categorical(
                    sub, logits[:, 0] / temperature, axis=-1)
            else:
                nxt = jnp.argmax(logits[:, 0], axis=-1)
            nxt = nxt.astype(token.dtype)
            return (nxt, caches, pos + 1, key), nxt

        (_, caches, _, _), toks = jax.lax.scan(
            body, (token0, caches, jnp.asarray(pos0, jnp.int32), key),
            None, length=n)
        return toks.T, caches                       # [B, n]

    return decode_n


_DUMMY_KEY = None


def _greedy_dummy_key():
    """One shared placeholder key for the greedy specialization (never
    read) — building PRNGKey(0) per request would add a device dispatch
    to the very hot path the fusion exists to shrink."""
    global _DUMMY_KEY
    if _DUMMY_KEY is None:
        _DUMMY_KEY = jax.random.PRNGKey(0)
    return _DUMMY_KEY


def generate_fused(params, cfg: transformer.ModelConfig, prompt: jnp.ndarray,
                   max_new_tokens: int = 32,
                   temperature: float = 0.0,
                   key: Optional[jax.Array] = None,
                   eos_id: Optional[int] = None) -> jnp.ndarray:
    """:func:`generate` with the whole decode loop fused into one
    device-resident scan.  Token streams are identical to ``generate``'s
    (same forwards, same argmax / same key-split sequence when
    sampling); with ``eos_id`` the post-EOS tail is masked host-side
    afterwards (the scan itself stays branch-free, so compute past an
    early EOS is spent, not saved — the continuous batcher is the tool
    when early exit matters)."""
    b, prompt_len = prompt.shape
    assert prompt_len + max_new_tokens <= cfg.max_seq, (
        f"{prompt_len}+{max_new_tokens} exceeds max_seq {cfg.max_seq}")
    if max_new_tokens < 1:
        return prompt                        # mirror generate(): no tokens
    if temperature > 0.0 and key is None:
        key = jax.random.PRNGKey(0)
    # sliding-window configs decode from a ROLLING window-sized cache:
    # O(window) HBM and attended keys instead of O(max_seq), with
    # bit-identical outputs (tests)
    caches = transformer.init_kv_caches(
        cfg, batch=b, rolling=transformer.wants_rolling(cfg))
    prefill, _ = make_decode_fns(cfg)
    logits, caches = prefill(params, prompt, caches, prompt_len)
    if temperature > 0.0:
        key, sub = jax.random.split(key)
        first = jax.random.categorical(sub, logits / temperature, axis=-1)
    else:
        first = jnp.argmax(logits, axis=-1)
    first = first.astype(prompt.dtype)
    pieces = [prompt, first[:, None]]
    if max_new_tokens > 1:
        n = max_new_tokens - 1
        # Bucket the static scan length to the next power of two (capped
        # by cache capacity) so organic max_new_tokens variance compiles
        # O(log max_seq) programs, not one per distinct length; the
        # surplus steps decode past the request and are sliced off
        # (causality: they cannot affect earlier tokens).
        n_run = 1
        while n_run < n:
            n_run *= 2
        n_run = min(n_run, cfg.max_seq - prompt_len - 1)
        rest, _ = make_fused_decode(cfg)(
            params, first, caches, prompt_len,
            key if temperature > 0.0 else _greedy_dummy_key(),
            jnp.float32(temperature if temperature > 0.0 else 1.0),
            n=n_run, sample=temperature > 0.0)
        pieces.append(rest[:, :n].astype(prompt.dtype))
    out = jnp.concatenate(pieces, axis=1)
    if eos_id is not None:
        gen = out[:, prompt_len:]
        seen = jnp.cumsum((gen == eos_id).astype(jnp.int32), axis=1)
        # positions strictly after the first EOS read as EOS
        gen = jnp.where((seen - (gen == eos_id)) > 0, eos_id, gen)
        out = jnp.concatenate([out[:, :prompt_len], gen], axis=1)
    return out


def generate(params, cfg: transformer.ModelConfig, prompt: jnp.ndarray,
             max_new_tokens: int = 32,
             temperature: float = 0.0,
             key: Optional[jax.Array] = None,
             eos_id: Optional[int] = None) -> jnp.ndarray:
    """prompt [B, P] -> [B, P + max_new_tokens] (greedy when T=0)."""
    b, prompt_len = prompt.shape
    assert prompt_len + max_new_tokens <= cfg.max_seq, (
        f"{prompt_len}+{max_new_tokens} exceeds max_seq {cfg.max_seq}")
    if temperature > 0.0 and key is None:
        key = jax.random.PRNGKey(0)
    caches = transformer.init_kv_caches(
        cfg, batch=b, rolling=transformer.wants_rolling(cfg))
    prefill, step = make_decode_fns(cfg)

    logits, caches = prefill(params, prompt, caches, prompt_len)
    out = [prompt]
    finished = jnp.zeros((b,), dtype=bool)
    for i in range(max_new_tokens):
        if temperature > 0.0:
            key, sub = jax.random.split(key)
            token = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            token = jnp.argmax(logits, axis=-1)
        if eos_id is not None:
            token = jnp.where(finished, eos_id, token)
            finished = finished | (token == eos_id)
        out.append(token[:, None])
        if eos_id is not None and bool(finished.all()):
            pad = jnp.full((b, max_new_tokens - i - 1), eos_id, prompt.dtype)
            if pad.shape[1]:
                out.append(pad)
            break
        logits, caches = step(params, token, caches, prompt_len + i)
    return jnp.concatenate(out, axis=1)
