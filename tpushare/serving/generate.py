"""Autoregressive generation: prefill + jitted single-token decode.

TPU-shaped decoding: the KV cache is a fixed-capacity buffer (static
shapes; one compile for prefill, one for the decode step regardless of
generation length), greedy or temperature sampling, early-exit handled
host-side so the jitted step stays branch-free.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..models import transformer


@functools.lru_cache(maxsize=8)
def make_decode_fns(cfg: transformer.ModelConfig):
    """(prefill_fn, step_fn), jitted once per config.

    Cached per cfg (hashable frozen dataclass): a fresh jit wrapper per
    call would key a new XLA cache entry per request and recompile on
    the serving hot path.
    """

    # Caches are donated: the caller always rebinds them, and in-place
    # XLA updates avoid holding two cache copies across the decode loop.
    @functools.partial(jax.jit, static_argnames=("prompt_len",),
                       donate_argnums=(2,))
    def prefill(params, tokens, caches, prompt_len: int):
        logits, caches = transformer.forward(
            params, tokens[:, :prompt_len], cfg, kv_caches=caches,
            cache_len=0)
        return logits[:, -1], caches

    @functools.partial(jax.jit, donate_argnums=(2,))
    def step(params, token, caches, pos):
        logits, caches = transformer.forward(
            params, token[:, None], cfg, kv_caches=caches, cache_len=pos)
        return logits[:, 0], caches

    return prefill, step


@functools.lru_cache(maxsize=8)
def make_fused_decode(cfg: transformer.ModelConfig):
    """Greedy multi-token decode: ONE jitted call scans ``n`` steps on
    device (token -> forward -> argmax -> next token) and returns all
    generated tokens.

    One host round trip per ``n`` tokens instead of per token — the
    difference between ~14 tokens/s (per-dispatch, ~70 ms RPC each on a
    tunnel-attached chip) and compute-limited decode.  Greedy only: the
    sampled path needs per-step host RNG bookkeeping and stays in
    :func:`generate`'s loop.
    """

    @functools.partial(jax.jit, static_argnames=("n",), donate_argnums=(2,))
    def decode_n(params, token0, caches, pos0, n: int):
        def body(carry, _):
            token, caches, pos = carry
            logits, caches = transformer.forward(
                params, token[:, None], cfg, kv_caches=caches,
                cache_len=pos)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(token.dtype)
            return (nxt, caches, pos + 1), nxt

        (_, caches, _), toks = jax.lax.scan(
            body, (token0, caches, jnp.asarray(pos0, jnp.int32)), None,
            length=n)
        return toks.T, caches                       # [B, n]

    return decode_n


def generate_fused(params, cfg: transformer.ModelConfig, prompt: jnp.ndarray,
                   max_new_tokens: int = 32,
                   eos_id: Optional[int] = None) -> jnp.ndarray:
    """Greedy :func:`generate` with the whole decode loop fused into one
    device-resident scan.  Token streams are identical to ``generate``'s
    (same forwards, same argmax); with ``eos_id`` the post-EOS tail is
    masked host-side afterwards (the scan itself stays branch-free, so
    compute past an early EOS is spent, not saved — the continuous
    batcher is the tool when early exit matters)."""
    b, prompt_len = prompt.shape
    assert prompt_len + max_new_tokens <= cfg.max_seq, (
        f"{prompt_len}+{max_new_tokens} exceeds max_seq {cfg.max_seq}")
    if max_new_tokens < 1:
        return prompt                        # mirror generate(): no tokens
    caches = transformer.init_kv_caches(cfg, batch=b)
    prefill, _ = make_decode_fns(cfg)
    logits, caches = prefill(params, prompt, caches, prompt_len)
    first = jnp.argmax(logits, axis=-1).astype(prompt.dtype)
    pieces = [prompt, first[:, None]]
    if max_new_tokens > 1:
        rest, _ = make_fused_decode(cfg)(
            params, first, caches, prompt_len, n=max_new_tokens - 1)
        pieces.append(rest.astype(prompt.dtype))
    out = jnp.concatenate(pieces, axis=1)
    if eos_id is not None:
        gen = out[:, prompt_len:]
        seen = jnp.cumsum((gen == eos_id).astype(jnp.int32), axis=1)
        # positions strictly after the first EOS read as EOS
        gen = jnp.where((seen - (gen == eos_id)) > 0, eos_id, gen)
        out = jnp.concatenate([out[:, :prompt_len], gen], axis=1)
    return out


def generate(params, cfg: transformer.ModelConfig, prompt: jnp.ndarray,
             max_new_tokens: int = 32,
             temperature: float = 0.0,
             key: Optional[jax.Array] = None,
             eos_id: Optional[int] = None) -> jnp.ndarray:
    """prompt [B, P] -> [B, P + max_new_tokens] (greedy when T=0)."""
    b, prompt_len = prompt.shape
    assert prompt_len + max_new_tokens <= cfg.max_seq, (
        f"{prompt_len}+{max_new_tokens} exceeds max_seq {cfg.max_seq}")
    if temperature > 0.0 and key is None:
        key = jax.random.PRNGKey(0)
    caches = transformer.init_kv_caches(cfg, batch=b)
    prefill, step = make_decode_fns(cfg)

    logits, caches = prefill(params, prompt, caches, prompt_len)
    out = [prompt]
    finished = jnp.zeros((b,), dtype=bool)
    for i in range(max_new_tokens):
        if temperature > 0.0:
            key, sub = jax.random.split(key)
            token = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            token = jnp.argmax(logits, axis=-1)
        if eos_id is not None:
            token = jnp.where(finished, eos_id, token)
            finished = finished | (token == eos_id)
        out.append(token[:, None])
        if eos_id is not None and bool(finished.all()):
            pad = jnp.full((b, max_new_tokens - i - 1), eos_id, prompt.dtype)
            if pad.shape[1]:
                out.append(pad)
            break
        logits, caches = step(params, token, caches, prompt_len + i)
    return jnp.concatenate(out, axis=1)
