"""``tpushare-llm-server`` — the workload that runs inside an allocation.

The BASELINE config 2-4 pod: enforce the tpushare env contract, apply
the HBM budget, build a (optionally int8) decoder model, and serve
generation over HTTP:

* ``POST /generate`` ``{"tokens": [[...]], "max_new_tokens": N,
  "temperature": T}`` → ``{"tokens": [[...]]}``
* ``GET /healthz`` / ``GET /stats``

Single-model single-process by design: process isolation between
co-tenants is the device plugin's job; this server only has to stay
inside its granted fraction (budget applied before jax initializes).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import threading
import time

log = logging.getLogger("tpushare.llm")


def build_model(model_name: str, quantize_int8: bool, seed: int = 0,
                quantize_int4: bool = False, kv_dtype: str = "bf16",
                attn_kernel: str = "xla", n_experts: int = 0,
                moe_top_k: int = 1, moe_every: int = 1):
    """``kv_dtype="int8"`` stores the serving KV cache quantized
    (per-token scales, ~2x sequences per HBM byte; decode is accuracy-
    bounded, not bit-identical — see DESIGN.md "Quantized KV").
    Orthogonal to the weight-only ``--int8``/``--int4`` flags.
    ``attn_kernel="pallas"`` reads paged KV pools through the fused
    Pallas decode kernel instead of the XLA gather (DESIGN.md "The
    paged decode kernel"); dense storage ignores it.
    ``n_experts > 0`` swaps every ``moe_every``-th FFN for a routed
    top-``moe_top_k`` expert block (DESIGN.md "Expert-parallel
    decode"); the named checkpoints stay dense unless asked."""
    import dataclasses

    import jax

    from ..models import transformer
    from ..ops import quant

    cfgs = {
        "llama2-7b": transformer.llama2_7b,
        "llama3-8b": transformer.llama3_8b,
        "mistral-7b": transformer.mistral_7b,
        "flagship-small": lambda: transformer.ModelConfig(
            vocab=32000, d_model=512, n_layers=4, n_heads=8, n_kv_heads=4,
            d_ff=1408, max_seq=512),
        "tiny": transformer.tiny,
        # sliding-window tiny: serves through the ROLLING slot pool
        # (window-sized KV slots; transformer.ModelConfig.window)
        "tiny-window": lambda: transformer.tiny(max_seq=128, window=16),
    }
    if model_name not in cfgs:
        raise ValueError(f"unknown model {model_name!r} "
                         f"(have {sorted(cfgs)})")
    if quantize_int8 and quantize_int4:
        raise ValueError("pick one of int8 / int4")
    cfg = cfgs[model_name]()
    if kv_dtype != "bf16":
        cfg = dataclasses.replace(cfg, kv_dtype=kv_dtype)
    if attn_kernel != "xla":
        cfg = dataclasses.replace(cfg, attn_kernel=attn_kernel)
    if n_experts:
        cfg = dataclasses.replace(cfg, n_experts=n_experts,
                                  moe_top_k=moe_top_k,
                                  moe_every=moe_every)
    params = transformer.init_params(jax.random.PRNGKey(seed), cfg)
    if quantize_int4:
        params = quant.quantize_params(params, bits=4)
    elif quantize_int8:
        params = quant.quantize_params(params)
    return cfg, params


class _CountedChunks:
    """Stream-body wrapper guaranteeing ``on_end`` fires EXACTLY once,
    whether the stream is fully consumed, closed mid-iteration, or
    closed before iteration ever starts (a generator closed un-started
    never runs its own ``finally`` — the leak that would pin the
    drain-progress in-flight counter forever)."""

    def __init__(self, inner, on_end):
        self._inner = inner
        self._on_end = on_end
        self._done = False

    def _finish(self):
        if not self._done:
            self._done = True
            self._on_end()

    def __iter__(self):
        try:
            for chunk in self._inner:
                yield chunk
        finally:
            self._finish()

    def close(self):
        close = getattr(self._inner, "close", None)
        try:
            if close is not None:
                close()
        finally:
            # even if the inner cleanup raises (e.g. cancel during a
            # concurrent shutdown), the count MUST release
            self._finish()


class LLMServer:
    def __init__(self, cfg, params, port: int = 8000,
                 addr: str = "0.0.0.0",
                 default_max_new: int = 32,
                 n_slots: int = 0,
                 page_size: int = 0,
                 n_pages: int = 0,
                 tp: int = 0,
                 sp: int = 0,
                 pp: int = 0,
                 pp_microbatches: int = 0,
                 ep: int = 0,
                 spec_k: int = 0,
                 prefix_cache: bool = False,
                 prefill_budget: int = 0,
                 mixed_step: bool = True,
                 spill_bytes: int = 0,
                 policy_client=None,
                 adapter_slots: int = 0,
                 adapter_rank: int = 8):
        """``n_slots > 0`` serves requests (greedy or sampled) through the
        continuous batcher; ``n_slots == 0`` uses the serialized
        per-request path.  ``page_size > 0`` stores the KV cache in a
        paged pool (``n_pages`` pages, default dense-equivalent).
        ``tp > 1`` builds a tensor-parallel mesh over the pod's visible
        devices and serves SPMD (requires --slots; params and KV storage
        shard per ``tpushare.parallel.mesh``).  ``spec_k > 0`` turns on
        opportunistic prompt-lookup speculation on every storage flavor
        (greedy-exact; greedy slots speculate, sampling slots ride the
        same dispatch; see ContinuousService).  ``prefill_budget`` caps
        the prompt tokens one MIXED service round coalesces into its
        single-dispatch prefill block (0 = two prefill chunks);
        ``mixed_step=False`` restores the sequential advance-then-fuse
        interleave.  ``adapter_slots > 0`` builds the multi-adapter
        LoRA pool (rank ``adapter_rank``): /generate accepts
        ``"adapter": <name>`` and a mixed-adapter batch still runs ONE
        dispatch per round; admissions naming a non-resident adapter
        against a fully-pinned pool answer 503 + Retry-After."""
        from .. import telemetry
        from ..telemetry.events import debug_events_route
        from ..telemetry.trace import debug_trace_route
        from ..utils.httpserver import JsonHTTPServer, RawBody

        self.cfg = cfg
        self.params = params
        self.default_max_new = default_max_new
        self._gen_lock = threading.Lock()   # decode caches are per-call;
        # serialize so co-tenant HBM stays bounded by one batch
        # POST /drain flips this: stop ADMITTING (503 on generate/
        # stream/score) while in-flight work runs to completion — the
        # graceful half of a rolling restart, and what the fleet
        # router's health eviction calls before dropping a replica.
        self._draining = threading.Event()
        # Tenant-policy enforcement (serving/policy.py PolicyClient, or
        # None): the admission gate answers 429 + Retry-After while the
        # client's refusal window (a daemon "refuse" verdict, bounded
        # backoff) is open, and the client's pacer rides the dispatch
        # guards — installed via the ContinuousService below, or
        # directly on the health monitor in per-request mode.
        self._policy_client = policy_client
        self._inflight = 0                  # requests inside a handler
        # its OWN lock: _gen_lock is held across whole device decodes
        # (direct mode holds it for the full fused generation), and
        # /drain + a draining /healthz must answer fast regardless —
        # a scrape-timeout router would transport-evict a busy replica
        self._inflight_lock = threading.Lock()
        self._service = None
        if tp > 1 and n_slots <= 0:
            # only the batcher path is mesh-aware; silently serving
            # unsharded would defeat the point of asking for tp
            raise ValueError("tp > 1 requires n_slots > 0 "
                             "(tensor-parallel serving rides the "
                             "continuous batcher)")
        self._adapter_slots = int(adapter_slots)
        if adapter_slots > 0 and n_slots <= 0:
            raise ValueError("adapter_slots > 0 requires n_slots > 0 "
                             "(multi-adapter serving rides the "
                             "continuous batcher)")
        if sp > 1 and (n_slots <= 0 or page_size <= 0):
            # position striping spreads PAGES over the mesh; only the
            # paged pool has pages to stripe
            raise ValueError("sp > 1 requires n_slots > 0 and "
                             "page_size > 0 (position striping is a "
                             "paged-pool feature)")
        if pp > 1 and n_slots <= 0:
            # only the batcher path is mesh-aware, same rule as tp
            raise ValueError("pp > 1 requires n_slots > 0 (pipeline-"
                             "parallel serving rides the continuous "
                             "batcher)")
        if ep > 1 and n_slots <= 0:
            raise ValueError("ep > 1 requires n_slots > 0 (expert-"
                             "parallel serving rides the continuous "
                             "batcher)")
        if ep > 1 and not getattr(cfg, "n_experts", 0):
            # an expert axis with no experts to place on it is a
            # config error, not a demotion — say so before jax spins up
            raise ValueError("ep > 1 requires an MoE config "
                             "(n_experts > 0)")
        # attn_kernel="pallas" + tp > 1 is served: the paged dispatcher
        # shard_maps the kernel over the tp axis (whole GQA head groups
        # per shard; ops.attention.sharded_paged_decode_attention) and
        # falls back to the sharded XLA gather — with the fallback
        # counter bumped — when the per-shard shapes fail the viability
        # gates (including indivisible head counts).
        if n_slots > 0:
            from .continuous import ContinuousService

            mesh = None
            if tp > 1 or sp > 1 or pp > 1 or ep > 1:
                from ..parallel.mesh import make_mesh
                axes = {}
                if tp > 1:
                    axes["tp"] = tp
                if sp > 1:
                    axes["sp"] = sp     # position striping (round 17)
                if pp > 1:
                    axes["pp"] = pp     # pipeline stages (round 21)
                if ep > 1:
                    axes["ep"] = ep     # expert sharding (round 22)
                mesh = make_mesh(axes)
            self._service = ContinuousService(
                params, cfg, n_slots,
                page_size=page_size or None,
                n_pages=n_pages or None,
                mesh=mesh,
                spec_k=spec_k,
                prefix_cache=prefix_cache,
                mixed_step=mixed_step,
                prefill_budget=prefill_budget or None,
                spill_bytes=spill_bytes or None,
                policy=(policy_client.pacer
                        if policy_client is not None else None),
                adapter_slots=adapter_slots,
                adapter_rank=adapter_rank,
                pp=max(1, pp),
                pp_microbatches=pp_microbatches or None).start()
            # Operator-visible kernel demotion (round 17 satellite): a
            # pallas config whose pool fails a viability gate (e.g. a
            # page_size=16 int8 pool's 32-row sublane tile) serves the
            # XLA gather on every tick — say so ONCE at startup instead
            # of leaving only the "(fb N)" metric to find.
            info = self._service.storage_info()
            reason = info.get("attn_fallback_reason")
            if reason:
                log.warning(
                    "attn_kernel='pallas' cannot run on this pool "
                    "(reason=%s): serving falls back to the XLA "
                    "gather read — see "
                    "tpushare_attn_kernel_fallback_total{reason=%r} "
                    "and the ATTN column in `kubectl inspect tpushare "
                    "--metrics`", reason, reason)
            pp_reason = info.get("pp_fallback_reason")
            if pp_reason:
                log.warning(
                    "pp=%d cannot run the microbatched stage program "
                    "on this config (reason=%s): layers still place "
                    "across the pp axis but every round runs the flat "
                    "program — see tpushare_attn_kernel_fallback_total"
                    "{reason=%r} and the STAGES column in `kubectl "
                    "inspect tpushare --metrics`", pp, pp_reason,
                    pp_reason)
            ep_reason = info.get("expert_fallback_reason")
            if ep_reason:
                log.warning(
                    "ep=%d cannot shard the expert pool on this "
                    "config (reason=%s): every rank holds the full "
                    "pool and the routed block runs unsharded — see "
                    "tpushare_expert_fallback_total{reason=%r} and "
                    "the EXPERTS column in `kubectl inspect tpushare "
                    "--metrics`", ep, ep_reason, ep_reason)
        if policy_client is not None and self._service is None:
            # per-request mode has no service lifecycle to ride: arm
            # the dispatch-guard pacer directly (the slot-pool path
            # installs through ContinuousService.start above; stop()
            # mirrors the disarm)
            from ..telemetry.health import MONITOR
            MONITOR.install_policy(policy_client.pacer)
        self.requests_served = 0
        self.sequences_served = 0
        self.tokens_generated = 0
        self._t0 = time.monotonic()
        self._http = JsonHTTPServer(port, addr, routes={
            ("POST", "/generate"): self._generate,
            ("POST", "/generate_stream"): self._generate_stream,
            ("POST", "/score"): self._score,
            # graceful drain: stop admitting, finish in-flight, report
            # drained in the /healthz body (rolling restarts; the fleet
            # router calls this on health eviction and undoes ITS
            # drains with {"undrain": true} on recovery)
            ("POST", "/drain"): self._drain,
            # KV-page migration receiver: scatter a peer's session blob
            # into this pool and decode it to completion (the decode
            # half of prefill/decode disaggregation, and the target of
            # /drain {"migrate_to": ...} hand-offs)
            ("POST", "/migrate_in"): self._migrate_in,
            # health-plane view: non-200 exactly when the backend is
            # WEDGED (a stalled dispatch past deadline / failed probe);
            # while draining the body carries draining/drained/inflight
            ("GET", "/healthz"): self._healthz,
            ("GET", "/stats"): self._stats,
            # workload-side telemetry: the serving-plane series this
            # process recorded (engine/batcher/paged/spec), Prometheus
            # text format — what `kubectl inspect tpushare --metrics`
            # scrapes per node
            ("GET", "/metrics"): self._metrics,
            # ?since=<seq> tails both rings incrementally (shared
            # route implementations with the daemon and the router)
            ("GET", "/debug/trace"): debug_trace_route,
            ("GET", "/debug/events"): debug_events_route,
        })
        self.port = self._http.port

    # -- drain plumbing ------------------------------------------------
    def _begin_request(self):
        """Admission gate shared by the request handlers: 503 while
        draining (the router's eviction contract — refusals here are
        what re-dispatch elsewhere), else count the request in-flight.
        Returns the refusal response or None."""
        with self._inflight_lock:
            # check-and-increment atomically vs _drain's flag set (same
            # lock): otherwise a request admitted between the check and
            # the increment could be invisible to a drained:true
            # /healthz and die with the pod.  DRAINING wins over the
            # policy refusal: the router's eviction/re-dispatch
            # contract string-matches the 503 draining body, and a
            # 429 here would read as an application answer instead of
            # "serve it elsewhere".
            if self._draining.is_set():
                return 503, {"Error": "draining: not admitting new "
                                      "requests"}
            if self._policy_client is not None:
                # tenant-policy refusal window (a daemon "refuse"
                # verdict): 429 + Retry-After, bounded backoff, fully
                # re-submittable — the request never reaches the
                # device, so a refused tenant stops costing the chip
                # anything at all
                retry_s = self._policy_client.refusal_retry_after()
                if retry_s > 0:
                    from . import metrics
                    metrics.POLICY_REFUSALS.inc()
                    return (429,
                            {"Error": "admission refused by tenant "
                                      "policy (device-time share over "
                                      "entitlement); retry after the "
                                      "indicated backoff"},
                            {"Retry-After":
                             str(max(1, int(retry_s + 0.5)))})
            self._inflight += 1
        return None

    def _end_request(self):
        with self._inflight_lock:
            self._inflight = max(0, self._inflight - 1)

    def _drain_snapshot(self) -> dict:
        """The drain-progress fields /drain and a draining /healthz
        report: handler-level in-flight plus whatever the slot pool
        still holds (a stream counts in BOTH until its batcher work and
        its consumer finish — 'drained' means every view hit zero)."""
        with self._inflight_lock:
            inflight = self._inflight
        pending = inflight
        if self._service is not None:
            s = self._service.snapshot()
            pending += s["active"] + s["prefilling"] + s["queued"]
        return {"draining": self._draining.is_set(),
                "inflight": inflight,
                "drained": self._draining.is_set() and pending == 0}

    def _drain(self, body=None):
        """``{}`` drains; ``{"undrain": true}`` re-admits — drains must
        be REVERSIBLE or a router-evicted replica that recovers would
        503 forever (the fleet router undrains exactly the replicas it
        drained; an operator's rolling-restart drain ends with the
        process, so nothing else ever needs to undo it).
        ``{"migrate_to": "host:port"}`` additionally MOVES in-flight
        decoding sessions to the named peer (KV-page migration) instead
        of waiting them out: each session's blob POSTs to the peer's
        /migrate_in, the peer decodes it to completion, and this
        process proxies the finished stream back to its still-connected
        client — the fast half of a rolling restart.  A peer refusal
        resumes the session locally (in-flight work always finishes
        somewhere)."""
        migrate_to = None
        with self._inflight_lock:       # atomic vs _begin_request
            if isinstance(body, dict) and body.get("undrain"):
                was = self._draining.is_set()
                self._draining.clear()
                if was:
                    log.info("undrained: admission re-opened")
            else:
                was = self._draining.is_set()
                self._draining.set()
                if not was:
                    log.info("draining: admission stopped; in-flight "
                             "requests run to completion")
                if isinstance(body, dict):
                    migrate_to = body.get("migrate_to") or None
        snap = self._drain_snapshot()
        if migrate_to is not None:
            if self._service is None or \
                    not self._service.can_migrate():
                from . import metrics
                metrics.MIGRATION_REFUSED.inc(
                    reason="unsupported_storage")
                snap["migrating_to"] = None
                snap["Error"] = ("migrate_to needs paged slot-pool "
                                 "serving (--slots + --page-size)")
            else:
                threading.Thread(
                    target=self._migrate_sessions, args=(migrate_to,),
                    daemon=True,
                    name="tpushare-drain-migrate").start()
                snap["migrating_to"] = migrate_to
        return 200, snap

    def _migrate_sessions(self, target: str) -> None:
        """Move every decoding session to ``target`` (host:port), one
        blob at a time, proxying each finished stream back to the
        local client.  A transfer failure re-imports the session
        locally and stops — the remaining sessions drain the classic
        way (run to completion here)."""
        import urllib.request

        from . import migrate
        if "://" not in target:
            target = f"http://{target}"
        moved = 0
        while True:
            got = self._service.migrate_out()
            if got is None:
                break
            rid, blob = got
            try:
                req = urllib.request.Request(
                    f"{target}/migrate_in",
                    data=json.dumps(
                        {"blob": migrate.encode_blob(blob)}).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=600) as resp:
                    payload = json.loads(resp.read())
                tokens = payload["tokens"][0]
            except Exception as e:
                log.warning("session %d hand-off to %s failed (%s); "
                            "resuming locally", rid, target, e)
                self._service.reimport(rid, blob)
                break
            self._service.deliver_migrated(rid, tokens)
            moved += 1
        if moved:
            log.info("drain migrated %d session(s) to %s", moved,
                     target)

    def _migrate_in(self, body):
        refused = self._begin_request()
        if refused is not None:
            return refused
        try:
            return self._migrate_in_impl(body)
        finally:
            self._end_request()

    def _migrate_in_impl(self, body):
        """Import a migration blob and serve the session to COMPLETION:
        responds like /generate (``{"tokens": [[...]]}``, the full
        stream including what the sender already generated), so drain
        senders and the disaggregating router can proxy the result
        straight back to the original client.  Refusals answer 409
        (the router's local-decode-fallback trigger) with the counted
        reason.  The 200 payload carries ``served_s`` — this handler's
        import+decode wall — which the disaggregating router POPS to
        split its hand-off hop into decode_ttft vs migration_wire
        (one-shot delivery makes the serve wall the TTFT)."""
        import queue as _q

        from . import metrics, migrate

        t_in = time.perf_counter()

        if self._service is None or \
                not self._service.can_migrate():
            metrics.MIGRATION_REFUSED.inc(reason="unsupported_storage")
            return 409, {"Error": "migration refused: "
                                  "unsupported_storage (this replica "
                                  "runs without --slots/--page-size)"}
        data = body.get("blob") if isinstance(body, dict) else None
        if not isinstance(data, str) or not data:
            return 400, {"Error": "body must carry blob: <base64>"}
        try:
            blob = migrate.decode_blob(data)
            arrived = len(migrate.blob_meta(blob)["slot"]["output"])
        except (migrate.BlobError, KeyError, TypeError):
            metrics.MIGRATION_REFUSED.inc(reason="bad_blob")
            return 400, {"Error": "migration refused: bad_blob"}
        sink = self._service.import_session(blob)
        try:
            out = sink.get(timeout=600)
        except _q.Empty:
            return 504, {"Error": "migrated session timed out"}
        if out is None:
            return 503, {"Error": "server shutting down"}
        if isinstance(out, tuple) and out and out[0] == "refused":
            return 409, {"Error": f"migration refused: {out[1]}"}
        with self._gen_lock:
            self.requests_served += 1
            self.sequences_served += 1
            # only the tokens THIS replica decoded count here; the
            # sender's share is in its own stats
            self.tokens_generated += max(0, len(out) - arrived)
        return 200, {"tokens": [out],
                     "served_s": time.perf_counter() - t_in}

    def _healthz(self, _body=None):
        from ..telemetry.health import MONITOR
        code, body = MONITOR.healthz()
        if not self._draining.is_set():
            return code, body
        if isinstance(body, str):          # the bare-OK fast path
            body = {"state": "ok"}
        body = dict(body)
        body.update(self._drain_snapshot())
        return code, body

    def _generate(self, body):
        refused = self._begin_request()
        if refused is not None:
            return refused
        try:
            return self._generate_impl(body)
        finally:
            self._end_request()

    def _generate_impl(self, body):
        import jax
        import jax.numpy as jnp

        if "text" in body and body.get("tokens") is not None:
            return 400, {"Error": "send either text or tokens, not both"}
        text_mode = "text" in body
        if text_mode:
            from .tokenizer import VOCAB_FLOOR, ByteTokenizer

            if self.cfg.vocab < VOCAB_FLOOR:
                return 400, {"Error": "model vocab too small for the "
                                      "byte tokenizer; send tokens"}
            text = body.get("text")
            if not isinstance(text, str) or not text:
                return 400, {"Error": "text must be a non-empty string"}
            body = dict(body)
            body["tokens"] = [ByteTokenizer().encode(text)]
        tokens = body.get("tokens")
        if (not tokens or not isinstance(tokens, list)
                or not all(isinstance(row, list) and row for row in tokens)):
            return 400, {"Error": "body must contain tokens: [[int, ...]]"}
        if self._service is None and len({len(row) for row in tokens}) != 1:
            # the per-request path decodes rows as one rectangular batch;
            # the slot pool serves each row independently, so ragged rows
            # are fine there
            return 400, {"Error": "token rows must share one length "
                                  "(pad client-side, or run with --slots)"}
        fields, err = self._parse_gen_fields(body)
        if err is not None:
            return err
        max_new = fields["max_new"]
        temperature = fields["temperature"]
        seed = fields["seed"]
        eos_id = fields["eos_id"]
        top_k = fields["top_k"]
        top_p = fields["top_p"]
        try:
            flat = [int(t) for row in tokens for t in row]
        except (TypeError, ValueError) as e:
            return 400, {"Error": f"malformed field: {e}"}
        if any(t < 0 or t >= self.cfg.vocab for t in flat):
            return 400, {"Error": f"token id out of range [0, "
                                  f"{self.cfg.vocab})"}
        if max(len(row) for row in tokens) + max_new > self.cfg.max_seq:
            return 400, {"Error": f"prompt+max_new_tokens exceeds "
                                  f"max_seq={self.cfg.max_seq}"}
        phase = body.get("phase", "full")
        if phase not in ("full", "prefill"):
            return 400, {"Error": "phase must be 'full' or 'prefill'"}
        if phase == "prefill":
            return self._generate_prefill_only(tokens, fields)
        if self._service is not None:
            adapter = fields["adapter"]
            if adapter and self._service.adapter_pressure(adapter):
                # adapter-pool pressure: every pool row pinned by an
                # in-flight request and this name not resident — the
                # usual bounded-backoff refusal (re-submittable; pins
                # release as requests complete, and the fleet router
                # re-dispatches a 503 to a replica that may already
                # hold the adapter)
                return (503,
                        {"Error": "adapter pool at capacity (every "
                                  "resident adapter pinned by an "
                                  "in-flight request); retry after "
                                  "the indicated backoff"},
                        {"Retry-After": "2"})
            # greedy and sampling both ride the slot pool (per-slot
            # temperature/keys) — no second KV cache beside the pool
            # Derive a per-row seed: identical prompts in one request must
            # sample independently, matching the batch path where one key
            # yields independent per-row draws.
            sinks = [self._service.submit([int(t) for t in row], max_new,
                                          temperature=temperature,
                                          seed=seed + i, eos_id=eos_id,
                                          top_k=top_k, top_p=top_p,
                                          adapter=adapter,
                                          trace=fields["trace"])
                     for i, row in enumerate(tokens)]
            import queue as _q

            try:
                rows = [s.get(timeout=600) for s in sinks]
            except _q.Empty:
                return 504, {"Error": "generation timed out"}
            if any(r is None for r in rows):
                return 503, {"Error": "server shutting down"}
            with self._gen_lock:
                self.requests_served += 1
                self.sequences_served += len(tokens)
                # actual production, not the cap: eos can stop early
                self.tokens_generated += sum(
                    len(r) - len(row) for r, row in zip(rows, tokens))
            return 200, self._result(rows, text_mode)

        key = jax.random.PRNGKey(seed)
        prompt = jnp.asarray(tokens, dtype=jnp.int32)
        with self._gen_lock:
            # the whole decode loop is one device-resident scan (one host
            # round trip per request, not per token); streams are
            # identical to the per-token loop path (tested)
            from .generate import generate_fused
            out = generate_fused(self.params, self.cfg, prompt,
                                 max_new_tokens=max_new,
                                 temperature=temperature, key=key,
                                 eos_id=eos_id)
            rows = [list(map(int, row)) for row in out]
            if eos_id is not None:
                # generate_fused masks the post-eos tail to eos_id at
                # FULL length; the HTTP contract is the slot-pool one —
                # truncate after the first generated eos so both server
                # modes answer identically
                cut = []
                for row, src_row in zip(rows, tokens):
                    gen = row[len(src_row):]
                    if eos_id in gen:
                        row = row[:len(src_row) + gen.index(eos_id) + 1]
                    cut.append(row)
                rows = cut
            self.requests_served += 1
            self.sequences_served += len(tokens)
            self.tokens_generated += sum(
                len(r) - len(row) for r, row in zip(rows, tokens))
        return 200, self._result(rows, text_mode)

    def _generate_prefill_only(self, tokens, fields):
        """The disaggregation SENDER half of /generate: prefill the
        prompt, sample the first token, and answer with the exported
        session blob (``{"migration": <base64>}``) for the router to
        stream to a decode replica's /migrate_in — or, when the
        request COMPLETES at activation (max_new 1 / instant eos),
        with the finished tokens like a plain /generate."""
        import queue as _q

        from . import migrate

        if self._service is None or \
                not self._service.can_migrate():
            return 400, {"Error": "phase='prefill' needs paged "
                                  "slot-pool serving (--slots + "
                                  "--page-size)"}
        if len(tokens) != 1:
            return 400, {"Error": "phase='prefill' takes exactly one "
                                  "prompt row"}
        if fields["adapter"] and self._service.adapter_pressure(
                fields["adapter"]):
            return (503, {"Error": "adapter pool at capacity; retry "
                                   "after the indicated backoff"},
                    {"Retry-After": "2"})
        sink = self._service.submit_handoff(
            [int(t) for t in tokens[0]], fields["max_new"],
            temperature=fields["temperature"], seed=fields["seed"],
            eos_id=fields["eos_id"], top_k=fields["top_k"],
            top_p=fields["top_p"], adapter=fields["adapter"],
            trace=fields["trace"])
        try:
            out = sink.get(timeout=600)
        except _q.Empty:
            return 504, {"Error": "prefill timed out"}
        if out is None:
            return 503, {"Error": "server shutting down"}
        with self._gen_lock:
            self.requests_served += 1
            self.sequences_served += 1
            self.tokens_generated += 1     # the sampled first token
        if isinstance(out, tuple) and out and out[0] == "handoff":
            return 200, {"migration": migrate.encode_blob(out[1])}
        return 200, {"tokens": [out]}      # completed at activation

    def _parse_gen_fields(self, body):
        """The ONE parse/validate path for /generate and /generate_stream
        (fields must not drift between endpoints): returns
        (fields_dict, None) or (None, (code, error_payload))."""
        from .continuous import ContinuousBatcher

        try:
            f = {
                "max_new": int(body.get("max_new_tokens",
                                        self.default_max_new)),
                "temperature": float(body.get("temperature", 0.0)),
                "seed": int(body.get("seed", 0)),
                "top_k": int(body.get("top_k", 0)),
                "top_p": float(body.get("top_p", 1.0)),
            }
            eos = body.get("eos_id")
            f["eos_id"] = int(eos) if eos is not None else None
        except (TypeError, ValueError) as e:
            return None, (400, {"Error": f"malformed field: {e}"})
        adapter = body.get("adapter")
        if adapter is not None and (not isinstance(adapter, str)
                                    or not adapter):
            return None, (400, {"Error": "adapter must be a non-empty "
                                         "string"})
        f["adapter"] = adapter
        if adapter and not self._adapter_slots:
            return None, (400, {"Error": "adapter serving needs the "
                                         "adapter pool; run with "
                                         "--slots and --adapter-slots"})
        if f["max_new"] < 1:
            return None, (400, {"Error": "max_new_tokens must be >= 1"})
        if (f["eos_id"] is not None
                and not 0 <= f["eos_id"] < self.cfg.vocab):
            return None, (400, {"Error": f"eos_id out of range [0, "
                                         f"{self.cfg.vocab})"})
        try:
            ContinuousBatcher.validate_sampling(f["top_k"], f["top_p"])
        except ValueError as e:
            return None, (400, {"Error": str(e)})
        if (f["top_k"] or f["top_p"] < 1.0) and self._service is None:
            return None, (400, {"Error": "top_k/top_p need the slot "
                                         "pool; run with --slots"})
        # fleet trace context (router-stamped or client-supplied):
        # malformed values are silently untraced — tracing never 400s
        # a request the replica would otherwise serve
        from ..telemetry import propagation
        ctx = propagation.extract(body)
        f["trace"] = ctx.trace_id if ctx else None
        return f, None

    def _score(self, body):
        refused = self._begin_request()
        if refused is not None:
            return refused
        try:
            return self._score_impl(body)
        finally:
            self._end_request()

    def _score_impl(self, body):
        """Teacher-forced scoring: per-token log-probabilities of given
        sequences under the model — the eval-workload endpoint
        (perplexity, reranking, answer scoring).  One forward per
        request; no sampling, no cache.

        ``{"tokens": [[...], ...]}`` scores each row's tokens[1:] given
        its prefix; optional ``{"prompt_len": P}`` restricts the summed
        score to positions >= P (score a continuation given a prompt).
        Rows must share one length.  Returns per-row
        ``{"logprobs": [...], "total": t, "scored_tokens": n}``.
        """
        import jax.numpy as jnp

        from .score import score_tokens

        tokens = body.get("tokens")
        if (not tokens or not isinstance(tokens, list)
                or not all(isinstance(r, list) and len(r) >= 2
                           for r in tokens)):
            return 400, {"Error": "body must contain tokens: "
                                  "[[int, int, ...], ...] (>= 2 tokens)"}
        if len({len(r) for r in tokens}) != 1:
            return 400, {"Error": "token rows must share one length"}
        try:
            rows = [[int(t) for t in r] for r in tokens]
            prompt_len = int(body.get("prompt_len", 1))
        except (TypeError, ValueError) as e:
            return 400, {"Error": f"malformed field: {e}"}
        flat = [t for r in rows for t in r]
        if any(t < 0 or t >= self.cfg.vocab for t in flat):
            return 400, {"Error": f"token id out of range [0, "
                                  f"{self.cfg.vocab})"}
        if len(rows[0]) > self.cfg.max_seq:
            return 400, {"Error": f"sequence exceeds max_seq="
                                  f"{self.cfg.max_seq}"}
        if not 1 <= prompt_len < len(rows[0]):
            return 400, {"Error": "prompt_len must be in [1, len-1]"}
        if self._service is not None and self._service.mesh \
                is not None:
            # tp serving shards the BATCHER's param copy; self.params is
            # the unsharded original, and a model needing tp won't fit
            # (or shouldn't double-exist) on one device
            return 400, {"Error": "/score is not mesh-aware yet; "
                                  "run without --tp to score"}
        with self._gen_lock:
            lp = score_tokens(self.params, self.cfg,
                              jnp.asarray(rows, jnp.int32))
            # the HOST FETCH is the real completion barrier (CLAUDE.md:
            # block_until_ready is unreliable on remote backends), so it
            # must happen INSIDE the lock for the lock to actually bound
            # device residency to one in-flight batch
            rows_lp = [[round(float(x), 4) for x in lp[i]]
                       for i in range(len(rows))]
            self.requests_served += 1
            self.sequences_served += len(rows)
        out = []
        for row_lp in rows_lp:
            scored = row_lp[prompt_len - 1:]
            out.append({"logprobs": scored,
                        "total": round(sum(scored), 4),
                        "scored_tokens": len(scored)})
        return 200, {"scores": out}

    def _generate_stream(self, body):
        from ..utils.httpserver import StreamingBody

        refused = self._begin_request()
        if refused is not None:
            return refused
        try:
            out = self._generate_stream_impl(body)
        except BaseException:
            self._end_request()            # a leak here would pin
            raise                          # /healthz drained:false forever
        code, payload = out[0], out[1]
        if not isinstance(payload, StreamingBody):
            self._end_request()            # refused before streaming
            return out                     # may carry headers (503s)
        # the request stays in-flight until the stream ends — done,
        # abort, client disconnect, or closed before the first chunk
        # (the httpserver's finally calls .close() on every path)
        payload.chunks = _CountedChunks(payload.chunks,
                                        self._end_request)
        return code, payload

    def _generate_stream_impl(self, body):
        """NDJSON token streaming over the slot pool: one line per decode
        progress event — {"delta": [new tokens...]} as they are produced
        (chunk granularity under fused decode), then {"done": [full
        row]}.  Single prompt per request; tokens only (byte-tokenizer
        text can split multibyte sequences across deltas, so decoding is
        the client's call)."""
        from ..utils.httpserver import StreamingBody

        if self._service is None:
            return 400, {"Error": "streaming needs the slot pool; run "
                                  "with --slots"}
        tokens = body.get("tokens")
        if (not tokens or not isinstance(tokens, list) or len(tokens) != 1
                or not isinstance(tokens[0], list) or not tokens[0]):
            return 400, {"Error": "body must contain tokens: [[int, ...]] "
                                  "with exactly one row"}
        fields, err = self._parse_gen_fields(body)
        if err is not None:
            return err
        max_new = fields["max_new"]
        temperature = fields["temperature"]
        seed = fields["seed"]
        eos_id = fields["eos_id"]
        top_k = fields["top_k"]
        top_p = fields["top_p"]
        try:
            row = [int(t) for t in tokens[0]]
        except (TypeError, ValueError) as e:
            return 400, {"Error": f"malformed field: {e}"}
        if any(t < 0 or t >= self.cfg.vocab for t in row):
            return 400, {"Error": f"token id out of range [0, "
                                  f"{self.cfg.vocab})"}
        if len(row) + max_new > self.cfg.max_seq:
            return 400, {"Error": f"prompt+max_new_tokens exceeds "
                                  f"max_seq={self.cfg.max_seq}"}
        # Stats are accounted when the BATCHER completes the request (on
        # the service loop thread), not when the client consumes the
        # stream to "done" — a disconnected client's request still ran
        # and must still count in /stats.
        def on_complete(out):
            with self._gen_lock:
                self.requests_served += 1
                self.sequences_served += 1
                self.tokens_generated += len(out) - len(row)

        if fields["adapter"] and self._service.adapter_pressure(
                fields["adapter"]):
            return (503, {"Error": "adapter pool at capacity; retry "
                                   "after the indicated backoff"},
                    {"Retry-After": "2"})
        sink = self._service.submit_stream(
            row, max_new, temperature=temperature, seed=seed,
            eos_id=eos_id, top_k=top_k, top_p=top_p,
            on_complete=on_complete, adapter=fields["adapter"],
            trace=fields["trace"])
        import queue as _q

        def chunks():
            finished = False
            try:
                while True:
                    try:
                        kind, val = sink.get(timeout=600)
                    except _q.Empty:
                        yield (json.dumps({"Error": "timeout"})
                               + "\n").encode()
                        return
                    if kind == "delta":
                        yield (json.dumps({"delta": val}) + "\n").encode()
                    elif kind == "done":
                        finished = True
                        yield (json.dumps({"done": val}) + "\n").encode()
                        return
                    else:
                        finished = True   # service shutdown; nothing left
                        yield (json.dumps({"Error": "aborted"})
                               + "\n").encode()
                        return
            finally:
                # Abandoned stream (client disconnect -> the server
                # closes this generator, or the sink timed out): release
                # the slot instead of decoding to completion for nobody.
                if not finished:
                    self._service.cancel(sink)

        return 200, StreamingBody(chunks())

    @staticmethod
    def _result(rows, text_mode: bool):
        payload = {"tokens": rows}
        if text_mode:
            from .tokenizer import ByteTokenizer

            tok = ByteTokenizer()
            payload["text"] = [tok.decode(row) for row in rows]
        return payload

    def _refresh_qps(self) -> float:
        """Mirror the served rate into the registry at read time, so a
        /metrics-only scraper (inspect --metrics) sees a live value,
        not whatever the last /stats poll froze in."""
        from . import metrics
        dt = time.monotonic() - self._t0
        if dt:
            metrics.QPS.set(round(self.requests_served / dt, 3))
        return dt

    def _metrics(self, _):
        from .. import telemetry
        from ..telemetry import health
        from ..utils.httpserver import RawBody
        self._refresh_qps()
        # scrape-time derivation: the goodput gauge always reflects the
        # device-time histograms as of THIS exposition
        health.refresh_device_utilization()
        return 200, RawBody(telemetry.REGISTRY.render(),
                            telemetry.PROM_CONTENT_TYPE)

    def _stats(self, _):
        dt = self._refresh_qps()
        stats = {
            "requests_served": self.requests_served,
            "sequences_served": self.sequences_served,
            "tokens_generated": self.tokens_generated,
            "uptime_s": round(dt, 1),
            "tokens_per_s": round(self.tokens_generated / dt, 2) if dt else 0,
        }
        if self._service is not None:
            stats["batcher"] = self._service.snapshot()
            # KV storage economics (what a slot/page costs, slots per
            # GiB) — the number the rolling pool / page ring change
            stats["kv_storage"] = self._service.storage_info()
        return 200, stats

    def start(self):
        self._http.start()
        return self

    def serve_forever(self):
        self._http.serve_forever()

    def stop(self):
        self._http.stop()
        if self._service is not None:
            self._service.stop()
        elif self._policy_client is not None:
            from ..telemetry.health import MONITOR
            MONITOR.uninstall_policy(self._policy_client.pacer)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpushare-llm-server",
        description="LLM generation server for a tpushare allocation")
    ap.add_argument("--model", default="flagship-small")
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 (the 14GiB Llama-2-7B config)")
    ap.add_argument("--int4", action="store_true",
                    help="weight-only grouped int4, packed two-per-byte "
                         "(a 7B model in a ~7GiB grant)")
    ap.add_argument("--kv-dtype", choices=("bf16", "int8"), default="bf16",
                    help="KV-cache storage dtype: int8 admits ~2x the "
                         "concurrent sequences per HBM byte (accuracy-"
                         "bounded decode, not bit-identical); works with "
                         "every storage flavor and composes with "
                         "--int8/--int4 weights")
    ap.add_argument("--attn-kernel", choices=("xla", "pallas"),
                    default="xla",
                    help="paged-pool attention read path: 'pallas' fuses "
                         "the page gather, int8 dequant, and online "
                         "softmax into one Pallas pass (no dense "
                         "transient; accuracy-bounded vs 'xla', not "
                         "bit-identical); needs --page-size to matter "
                         "(dense storage ignores it); composes with "
                         "--tp (the kernel runs per shard via "
                         "shard_map; indivisible head counts fall back "
                         "to the sharded gather)")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--addr", default="0.0.0.0")
    ap.add_argument("--slots", type=int, default=0,
                    help="continuous-batching slot count (0 = serialized "
                         "per-request decoding)")
    ap.add_argument("--page-size", type=int, default=0,
                    help="KV-cache page size in tokens (0 = dense per-slot "
                         "cache); requires --slots")
    ap.add_argument("--kv-pages", type=int, default=0,
                    help="paged-KV pool size in pages (0 = dense-equivalent "
                         "capacity); only with --page-size")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel degree over the pod's visible "
                         "devices (0/1 = single device); requires --slots")
    ap.add_argument("--sp", type=int, default=0,
                    help="position-striping degree: stripe every "
                         "sequence's KV pages round-robin across this "
                         "many mesh shards, multiplying per-sequence "
                         "max context and HBM by the degree (the "
                         "long-context knob — a sequence no longer "
                         "fits one shard's pool or nothing).  Requires "
                         "--slots and --page-size (full-causal models; "
                         "the windowed page ring cannot stripe); "
                         "composes with --tp (tp*sp devices), "
                         "--kv-dtype int8 (half the merge traffic), "
                         "--attn-kernel pallas (per-shard page walk + "
                         "online-softmax merge), --spec-k, and "
                         "session migration")
    ap.add_argument("--pp", type=int, default=0,
                    help="pipeline-parallel stage count: partition the "
                         "layer stack (params AND each layer's KV "
                         "storage — stage-local residency) across this "
                         "many mesh shards, and run the steady decode "
                         "step as a microbatched stage wavefront in "
                         "ONE dispatch per round (stage s decodes "
                         "microbatch m while stage s-1 decodes m+1).  "
                         "Requires --slots; streams are exactly the "
                         "unstaged server's.  Layer counts the stage "
                         "count does not divide, a >1 --tp/--sp axis, "
                         "or a rolling storage demote the wavefront to "
                         "placement-only sharding (counted, logged at "
                         "startup, still served)")
    ap.add_argument("--pp-microbatches", type=int, default=0,
                    help="microbatch count for the --pp wavefront (must "
                         "divide --slots; 0 = largest divisor of "
                         "--slots that is <= --pp)")
    ap.add_argument("--ep", type=int, default=0,
                    help="expert-parallel degree: shard an MoE "
                         "config's expert pool (gate/up/down stacks "
                         "and nothing else) across this many mesh "
                         "shards, each rank computing only its own "
                         "experts' contributions inside the one "
                         "batched dispatch (psum-merged routed "
                         "block; see DESIGN.md \"Expert-parallel "
                         "decode\").  Requires --slots and "
                         "--n-experts; composes with --tp/--sp "
                         "(tp*sp*ep devices).  Expert counts the "
                         "degree does not divide, or a >1 --pp "
                         "staged wavefront, demote to a replicated "
                         "pool (counted, logged at startup, still "
                         "served)")
    ap.add_argument("--n-experts", type=int, default=0,
                    help="serve an MoE variant of --model: swap every "
                         "--moe-every'th FFN for a routed block of "
                         "this many experts (0 = dense; per-token "
                         "top---moe-top-k routing inside the same "
                         "single-dispatch programs on every storage "
                         "flavor)")
    ap.add_argument("--moe-top-k", type=int, default=1,
                    help="experts each token routes to per MoE layer "
                         "(softmax-renormalized over the selected "
                         "gates; needs --n-experts)")
    ap.add_argument("--moe-every", type=int, default=1,
                    help="route every Nth layer's FFN through the "
                         "expert block, counting from layer 0 "
                         "(1 = all layers; needs --n-experts)")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="prompt-lookup speculation depth (0 = off; "
                         "greedy-exact; requires --slots).  Works on "
                         "EVERY storage flavor — dense, rolling ring, "
                         "--page-size pools incl. the windowed page "
                         "ring and --prefix-cache — and composes with "
                         "--kv-dtype int8, --attn-kernel pallas, and "
                         "--tp; greedy slots speculate while sampling "
                         "requests ride the same dispatch as plain "
                         "decode rows, and mixed admit-while-decode "
                         "rounds fuse prefill + speculation into one "
                         "dispatch.  A storage that cannot verify k "
                         "tokens (page ring without the eviction "
                         "margin) disables speculation with a counted "
                         "fallback instead of refusing to serve")
    ap.add_argument("--adapter-slots", type=int, default=0,
                    help="multi-adapter LoRA pool capacity: named "
                         "adapters resident per server (0 = off; "
                         "requires --slots).  /generate accepts "
                         "\"adapter\": <name>; each request's adapter "
                         "gathers per-row INSIDE the one batched "
                         "dispatch (two skinny matmuls per "
                         "projection), so thousands of tenants share "
                         "one resident base model instead of one "
                         "merged replica each.  Adapters load "
                         "on-demand (deterministic per name across "
                         "replicas), LRU-evict when unpinned, and "
                         "admissions against a fully-pinned pool "
                         "answer 503 + Retry-After")
    ap.add_argument("--adapter-rank", type=int, default=8,
                    help="LoRA rank of the serving adapter pool "
                         "(every resident adapter costs "
                         "rank*(d_in+d_out) per projection instead "
                         "of a merged model copy)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="reuse completed requests' prompt-prefix KV "
                         "pages for same-prefix admissions (requires "
                         "--page-size; full-causal models)")
    ap.add_argument("--spill-bytes", type=int, default=0,
                    help="host-RAM byte budget for the KV spill tier "
                         "(0 = off; requires --slots and --page-size): "
                         "admission past the pool's page capacity "
                         "parks the longest-resident session's KV in "
                         "host RAM and faults it back in when pressure "
                         "subsides — more concurrent sessions per HBM "
                         "byte, on top of --kv-dtype int8's ~2x.  "
                         "TPUSHARE_SPILL_IDLE_S sets the minimum "
                         "residency before a session may spill")
    ap.add_argument("--prefill-budget", type=int, default=0,
                    help="prompt tokens one mixed service round may "
                         "coalesce into its single-dispatch prefill "
                         "block (0 = two prefill chunks; requires "
                         "--slots)")
    ap.add_argument("--sequential-prefill", action="store_true",
                    help="disable the mixed prefill+decode step: one "
                         "dispatch per prefilling slot plus one fused "
                         "decode dispatch per round (the reference "
                         "interleave)")
    ap.add_argument("--policy", choices=("auto", "off"), default="auto",
                    help="tenant-isolation policy: 'auto' (default) "
                         "honors the daemon's /usage verdicts when "
                         "allocated under a TPUSHARE_STATUS_PORT "
                         "daemon running --tenant-policy enforce — "
                         "pace:<rate> verdicts token-bucket-pace the "
                         "device dispatches at the dispatch guard, "
                         "refuse verdicts answer 429 + Retry-After at "
                         "admission (bounded backoff, re-submittable); "
                         "'off' ignores verdicts entirely "
                         "(byte-identical pre-policy serving)")
    ap.add_argument("--pace-rate", type=float, default=0.0,
                    help="static self-pacing floor in device-seconds "
                         "per wall-second (0 = none): pace this "
                         "tenant's dispatches without any daemon — a "
                         "courtesy cap for a known-noisy batch tenant; "
                         "daemon pace verdicts override it while "
                         "active and an ok verdict restores it")
    args = ap.parse_args(argv)
    if args.pace_rate and args.policy == "off":
        # --policy off promises byte-identical pre-policy serving;
        # silently dropping an explicit self-pacing request would be
        # the worst of both
        ap.error("--pace-rate needs the policy machinery; drop "
                 "--policy off (auto self-paces without any daemon)")
    if args.spill_bytes and not args.page_size:
        ap.error("--spill-bytes requires --slots and --page-size")
    if args.prefill_budget and not args.slots:
        ap.error("--prefill-budget requires --slots")
    if args.sequential_prefill and not args.slots:
        ap.error("--sequential-prefill requires --slots")
    if args.prefix_cache and not args.page_size:
        ap.error("--prefix-cache requires --page-size")
    if args.spec_k and not args.slots:
        ap.error("--spec-k requires --slots")
    if args.adapter_slots and not args.slots:
        ap.error("--adapter-slots requires --slots")
    if args.page_size and not args.slots:
        ap.error("--page-size requires --slots")
    if args.kv_pages and not args.page_size:
        ap.error("--kv-pages requires --page-size")
    if args.tp > 1 and not args.slots:
        ap.error("--tp requires --slots")
    if args.sp > 1 and not (args.slots and args.page_size):
        ap.error("--sp requires --slots and --page-size")
    if args.pp > 1 and not args.slots:
        ap.error("--pp requires --slots")
    if args.pp_microbatches and args.pp <= 1:
        ap.error("--pp-microbatches requires --pp")
    if args.pp_microbatches and args.slots % args.pp_microbatches:
        ap.error("--pp-microbatches must divide --slots")
    if args.ep > 1 and not args.slots:
        ap.error("--ep requires --slots")
    if args.ep > 1 and not args.n_experts:
        ap.error("--ep requires --n-experts (an expert axis needs "
                 "experts to shard)")
    if (args.moe_top_k != 1 or args.moe_every != 1) and not args.n_experts:
        ap.error("--moe-top-k/--moe-every require --n-experts")
    logging.basicConfig(level=logging.INFO)

    # Contract first — fail fast with the scheduler's own words, and set
    # the HBM budget before jax initializes.
    from ..runtime import contract
    view = contract.enforce()
    contract.apply_memory_budget()
    if view.allocated:
        log.info("allocation: chip %s, %.0f%% HBM", view.chip_index,
                 (view.hbm_fraction or 1.0) * 100)
    else:
        log.info("running unallocated (dev mode)")

    cfg, params = build_model(args.model, args.int8,
                              quantize_int4=args.int4,
                              kv_dtype=args.kv_dtype,
                              attn_kernel=args.attn_kernel,
                              n_experts=args.n_experts,
                              moe_top_k=args.moe_top_k,
                              moe_every=args.moe_every)
    # Health plane: on a tunnel-attached backend, run the low-frequency
    # probe loop (tiny dispatch + scalar fetch with a deadline — the
    # true barrier) so /healthz reflects the tunnel, not hope.  A
    # local backend cannot wedge this way; the dispatch watchdog alone
    # covers it without burning probe dispatches.
    import os as _os

    from ..telemetry import health as _health
    if _os.environ.get("PALLAS_AXON_POOL_IPS"):
        # deadline covers the FIRST probe's remote_compile (~20-140 s
        # for bf16 through the tunnel, CLAUDE.md) — a tighter deadline
        # would mark a healthy warming server WEDGED on its first probe
        _health.MONITOR.start_probe_loop(
            interval_s=float(_os.environ.get(
                "TPUSHARE_PROBE_INTERVAL_S", "60")),
            deadline_s=float(_os.environ.get(
                "TPUSHARE_PROBE_DEADLINE_S", "180")))
    # Tenant policy (round 19): with --policy auto the daemon's /usage
    # verdicts drive a local PolicyClient — its pacer rides every
    # dispatch guard (installed through the service below) and its
    # refusal window gates admission with 429 + Retry-After.  A static
    # --pace-rate arms the same machinery without any daemon.
    policy_client = None
    reporting = bool(view.allocated
                     and _os.environ.get("TPUSHARE_STATUS_PORT"))
    interval = float(_os.environ.get("TPUSHARE_USAGE_REPORT_S", "30"))
    if args.policy != "off" and (args.pace_rate > 0 or reporting):
        from .policy import PolicyClient
        policy_client = PolicyClient(static_rate=args.pace_rate or None,
                                     verdict_interval_s=interval)
    srv = LLMServer(cfg, params, port=args.port, addr=args.addr,
                    n_slots=args.slots, page_size=args.page_size,
                    n_pages=args.kv_pages, tp=args.tp, sp=args.sp,
                    pp=args.pp, pp_microbatches=args.pp_microbatches,
                    ep=args.ep,
                    spec_k=args.spec_k, prefix_cache=args.prefix_cache,
                    prefill_budget=args.prefill_budget,
                    mixed_step=not args.sequential_prefill,
                    spill_bytes=args.spill_bytes,
                    policy_client=policy_client,
                    adapter_slots=args.adapter_slots,
                    adapter_rank=args.adapter_rank)
    # Tenant accounting: when the allocation injected a daemon status
    # port, report this tenant's usage (HBM peak + device-time/goodput/
    # qps/stalls, contract.report_usage) on a low-frequency loop — the
    # feed behind the daemon's per-tenant share-vs-entitlement view and
    # `kubectl inspect tpushare --tenants`.  Best-effort by contract
    # (report_usage never raises); daemon thread dies with the server.
    # The response carries the tenant-policy verdict; the PolicyClient
    # (when armed) closes the enforcement loop on each report.
    if reporting:
        def _report_loop():
            while True:
                time.sleep(interval)
                resp = contract.report_usage()
                if policy_client is not None and isinstance(resp, dict):
                    policy_client.apply(resp)

        threading.Thread(target=_report_loop, daemon=True,
                         name="tpushare-usage-report").start()
        log.info("usage reporting to daemon every %.0fs (policy: %s)",
                 interval, args.policy)
    log.info("llm server: model=%s quant=%s kv=%s tp=%d sp=%d pp=%d "
             "ep=%d experts=%d on :%d",
             args.model,
             "int4" if args.int4 else ("int8" if args.int8 else "none"),
             args.kv_dtype, args.tp, args.sp, args.pp, args.ep,
             args.n_experts, srv.port)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
