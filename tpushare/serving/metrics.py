"""Serving-plane metric handles (one definition per series).

Every serving module records into these shared handles, so the engine's
micro-batcher and the continuous batcher feed the SAME latency
histograms without importing each other (registration is get-or-create,
but defining each family exactly once keeps help text and buckets from
drifting).  Names follow the namespace lint: ``tpushare_`` prefix,
``_total`` for counters, ``_seconds`` for time histograms, ``_bytes``
for byte gauges (tests/test_metric_lint.py).

This module itself is stdlib-only (the jax-heavy modules import it, not
the other way around).
"""

from __future__ import annotations

from .. import telemetry
# Backend-attribution series live with the health plane (stdlib layer,
# shared with bench and the daemon); re-exported here so serving code
# keeps one metrics namespace to import from.
from ..telemetry.health import DEVICE_TIME, DEVICE_UTILIZATION  # noqa: F401

# -- request-level latency (engine micro-batcher AND continuous service) --
REQUEST_LATENCY = telemetry.histogram(
    "tpushare_engine_request_latency_seconds",
    "Submit-to-deliver latency per request through the serving plane")
TTFT = telemetry.histogram(
    "tpushare_engine_ttft_seconds",
    "Time to first output per request (first token for streaming decode; "
    "full result for one-shot batched inference)")
TPOT = telemetry.histogram(
    "tpushare_engine_tpot_seconds",
    "Per-token time per request (decode time per generated token for "
    "streaming; latency per sequence position for one-shot inference)",
    buckets=(1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
             5e-3, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0))
REQUESTS = telemetry.counter(
    "tpushare_engine_requests_total",
    "Requests submitted to the serving plane")
BATCHES = telemetry.counter(
    "tpushare_engine_batches_total",
    "Batches dispatched to the device (direct and micro-batched)")
BATCH_FILL = telemetry.gauge(
    "tpushare_engine_batch_fill",
    "Fraction of rows holding real requests in the last dispatched batch")
QPS = telemetry.gauge(
    "tpushare_engine_qps",
    "Queries/s: the most recent measure_qps result, or the serving "
    "process's lifetime served rate (refreshed at scrape time)")

# -- continuous batcher ---------------------------------------------------
TICK_DURATION = telemetry.histogram(
    "tpushare_tick_duration_seconds",
    "Wall time of one batcher tick call (single, fused, or speculative)")
OCCUPANCY = telemetry.gauge(
    "tpushare_batch_occupancy",
    "Active decoding slots / slot capacity after the last tick")
ADMISSIONS = telemetry.counter(
    "tpushare_admissions_total",
    "Requests admitted into a batcher slot")
COMPLETIONS = telemetry.counter(
    "tpushare_completions_total",
    "Requests finished by the batcher (slot released)")
CANCELLATIONS = telemetry.counter(
    "tpushare_cancellations_total",
    "Requests cancelled before completion (slot/storage reclaimed)")
FUSED_STEPS = telemetry.counter(
    "tpushare_fused_steps_total",
    "Decode steps executed inside fused (scan) tick chunks")

# -- mixed prefill+decode step --------------------------------------------
MIXED_STEPS = telemetry.counter(
    "tpushare_mixed_steps_total",
    "Mixed prefill+decode rounds dispatched (one device program each)")
MIXED_PREFILL_TOKENS = telemetry.counter(
    "tpushare_mixed_prefill_tokens_total",
    "Real prompt tokens coalesced into mixed-round prefill blocks")
MIXED_BUDGET_UTILIZATION = telemetry.gauge(
    "tpushare_mixed_budget_utilization",
    "Real prompt tokens / padded prefill-block capacity in the last "
    "mixed round (low = budget over-provisioned for current traffic)")
PREFILL_QUEUE_DEPTH = telemetry.gauge(
    "tpushare_prefill_queue_depth",
    "Slots currently mid-prefill (admitted, prompt not fully in cache)")

# -- speculation ----------------------------------------------------------
SPEC_PROPOSED = telemetry.counter(
    "tpushare_spec_proposed_total",
    "Draft/lookup tokens proposed to the verifier")
SPEC_ACCEPTED = telemetry.counter(
    "tpushare_spec_accepted_total",
    "Proposed tokens accepted by the target (acceptance rate = "
    "accepted/proposed)")
SPEC_ROUNDS = telemetry.counter(
    "tpushare_spec_rounds_total",
    "Batched speculative verify rounds executed")
SPEC_TOKENS = telemetry.counter(
    "tpushare_spec_tokens_total",
    "Tokens committed by batched speculative rounds")

# -- KV storage (all pool flavors) ----------------------------------------
KV_CACHE_BYTES = telemetry.gauge(
    "tpushare_kv_cache_bytes",
    "Persistent KV-cache pool HBM footprint of the live batcher (values "
    "plus int8 scale buffers; the bytes an int8 cache halves)")
KV_DTYPE_INFO = telemetry.gauge(
    "tpushare_kv_dtype_info",
    "KV-cache storage dtype of the live batcher (constant 1; the dtype "
    "rides the kv_dtype label, Prometheus info idiom)")
ATTN_KERNEL_INFO = telemetry.gauge(
    "tpushare_attn_kernel_info",
    "Attention read path of the live batcher's KV storage (constant 1; "
    "the path rides the attn_kernel label: 'xla' = dense gather, "
    "'pallas' = fused paged-decode kernel, Prometheus info idiom)")

# -- paged KV storage -----------------------------------------------------
KV_PAGES_USED = telemetry.gauge(
    "tpushare_kv_pages_used",
    "KV pool pages currently reserved (slots + cached prefixes)")
KV_PAGES_FREE = telemetry.gauge(
    "tpushare_kv_pages_free",
    "KV pool pages on the free list")
PREFIX_HITS = telemetry.counter(
    "tpushare_prefix_cache_hits_total",
    "Admissions that mapped a cached prompt prefix")
PREFIX_MISSES = telemetry.counter(
    "tpushare_prefix_cache_misses_total",
    "Prefix-cache-eligible admissions with no registered prefix")
