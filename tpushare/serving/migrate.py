"""KV-page migration: THE wire format for moving a live session's KV.

One serialized form — a length-prefixed binary blob holding a session's
committed KV pages (int8 pools ship their ``[page, 1]`` f32 scale
leaves alongside, so the transfer is ~half the bf16 bytes), its
page-table layout, and the full host-side slot state (output so far,
remaining budget, sampling knobs, and the CURRENT PRNG key data, so a
sampled stream resumes on the receiver exactly where it left off) —
shared by all three consumers:

* **prefill/decode disaggregation**: a prefill replica exports the
  session at the activation boundary and the router streams the blob to
  a decode replica's ``POST /migrate_in``;
* **live drain hand-off**: ``POST /drain {"migrate_to": url}`` moves
  in-flight sessions to a peer instead of waiting them out;
* **host-RAM spill tier**: idle/preempted sessions park their blob in
  the byte-budgeted :class:`HostSpillStore` and fault back in on their
  next turn.

This module is the ONE place KV wire (de)serialization lives
(lint-enforced: tpulint rule ``migration-wire-confinement`` — a second
hand-rolled codec would fork the format).  numpy + stdlib only, no jax:
the codec must be importable from processes that own no chip (tests,
tooling); the device gather/scatter halves live with the paged batcher
(:meth:`tpushare.serving.paged.PagedContinuousBatcher.export_session` /
``import_session``).

Why migrated streams stay exact: paged KV is position-indexed through
the page table, so copying the distinct pages a slot references
byte-for-byte and rebuilding the same table STRUCTURE (range -> local
page index) on the receiver reproduces identical attention reads — the
trash page, position masks, and past-the-end routing behave exactly as
they did on the sender (DESIGN.md "KV-page migration").  int8 pools
quantized at write time travel as their quantized bytes, so
re-serving them cannot re-round anything.
"""

from __future__ import annotations

import base64
import collections
import json
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

#: wire magic + format version (bump on any layout change; receivers
#: refuse unknown versions instead of guessing)
WIRE_MAGIC = b"TPUSKV1\n"
WIRE_VERSION = 1

#: every reason an incoming migration blob can be refused — the
#: enumerated values of ``tpushare_migration_refused_total{reason=}``
#: (enum-linted in tests/test_metric_lint.py like the other reason
#: families): ``pool_full`` = the receiver's page pool / slot pool
#: cannot fit the session right now (the router's local-decode-fallback
#: trigger); ``config_mismatch`` = the blob's model/storage fingerprint
#: differs from the receiver's (a blob is only portable between
#: same-model same-layout replicas); ``bad_blob`` = the bytes do not
#: parse as a versioned session blob; ``unsupported_storage`` = the
#: receiver serves a non-paged pool (dense slots have no page
#: primitive); ``spill_budget`` = the host-RAM spill store's byte
#: budget is exhausted, so the would-be victim stays resident instead
MIGRATION_REFUSAL_REASONS = ("pool_full", "config_mismatch", "bad_blob",
                             "unsupported_storage", "spill_budget")

#: the ``kind`` label values of ``tpushare_migrations_out_total`` /
#: ``tpushare_migrations_in_total`` (enum-linted): out = why a session
#: left this pool, in = how one arrived
MIGRATION_OUT_KINDS = ("handoff", "spill", "drain")
MIGRATION_IN_KINDS = ("import", "restore")

#: the ``direction`` label values of ``tpushare_migration_bytes_total``
#: (enum-linted through the declarative pin table in
#: tests/test_metric_lint.py, round 18): which way the blob bytes moved
MIGRATION_DIRECTIONS = ("in", "out")


class BlobError(ValueError):
    """The bytes are not a (known-version) session blob."""


class ConfigMismatch(ValueError):
    """The blob's model/storage fingerprint differs from the receiver's
    (the ``config_mismatch`` refusal: a session blob is only portable
    between same-model, same-layout replicas)."""


def config_fingerprint(cfg, page_size: int) -> dict:
    """The compatibility contract a blob carries: everything that must
    MATCH between sender and receiver for a page-for-page import to
    reproduce the same stream (model geometry, KV storage dtype, page
    geometry).  Duck-typed over ModelConfig so this module stays
    jax-free."""
    return {
        "vocab": int(cfg.vocab), "d_model": int(cfg.d_model),
        "n_layers": int(cfg.n_layers), "n_heads": int(cfg.n_heads),
        "n_kv_heads": int(cfg.n_kv_heads), "d_ff": int(cfg.d_ff),
        "max_seq": int(cfg.max_seq),
        "window": (int(cfg.window) if cfg.window is not None else None),
        "kv_dtype": str(cfg.kv_dtype),
        "page_size": int(page_size),
    }


def pack_session(meta: dict, arrays: "Dict[str, np.ndarray]") -> bytes:
    """``meta`` (JSON-serializable session state) + named numpy arrays
    -> one length-prefixed blob.

    Layout: magic | u64 header length | header JSON | raw array bytes
    (C-order, concatenated in directory order).  The header carries the
    array directory (name/dtype/shape/nbytes), so unpacking needs no
    second schema source."""
    directory: List[dict] = []
    payloads: List[bytes] = []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        # dtype travels by NAME, not .str: extension dtypes (jax's
        # bfloat16 via ml_dtypes) stringify as opaque void records
        # ("|V2") that cannot round-trip
        directory.append({"name": name, "dtype": a.dtype.name,
                          "shape": list(a.shape), "nbytes": a.nbytes})
        payloads.append(a.tobytes())
    header = json.dumps({"version": WIRE_VERSION, "meta": meta,
                         "arrays": directory},
                        sort_keys=True).encode()
    return b"".join([WIRE_MAGIC, struct.pack(">Q", len(header)), header]
                    + payloads)


def _parse_header(blob: bytes) -> Tuple[dict, int]:
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        raise BlobError("session blob must be bytes")
    blob = bytes(blob)
    if len(blob) < len(WIRE_MAGIC) + 8 or \
            blob[:len(WIRE_MAGIC)] != WIRE_MAGIC:
        raise BlobError("not a tpushare session blob (bad magic)")
    (hlen,) = struct.unpack(
        ">Q", blob[len(WIRE_MAGIC):len(WIRE_MAGIC) + 8])
    start = len(WIRE_MAGIC) + 8
    if len(blob) < start + hlen:
        raise BlobError("truncated session blob header")
    try:
        header = json.loads(blob[start:start + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise BlobError(f"unparsable session header: {e}") from None
    if header.get("version") != WIRE_VERSION:
        raise BlobError(f"unknown session blob version "
                        f"{header.get('version')!r}")
    return header, start + hlen


def blob_meta(blob: bytes) -> dict:
    """The session meta alone (receivers pre-validate compatibility and
    size the reservation before touching array bytes)."""
    header, _ = _parse_header(blob)
    return header["meta"]


def session_trace(meta: dict) -> "Optional[str]":
    """The fleet trace id a session header carries, or None — a
    migrated/disaggregated session's decode spans must join the
    ORIGINATING request's trace, so the opaque id (minted by
    :mod:`tpushare.telemetry.propagation`) rides the generic session
    meta with no wire-layout change and re-registers on import.
    Anything non-string (an old sender, a crafted header) is silently
    untraced — tracing never refuses a blob."""
    trace = meta.get("trace")
    return trace if isinstance(trace, str) and trace else None


def _wire_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16, float8_*) register with numpy on
        # import; ml_dtypes ships with jax but this module must not
        # import jax itself
        import ml_dtypes  # noqa: F401
        return np.dtype(name)


def unpack_session(blob: bytes) -> Tuple[dict, "Dict[str, np.ndarray]"]:
    """Blob -> (meta, {name: array}); raises :class:`BlobError` on any
    structural problem (the ``bad_blob`` refusal)."""
    header, off = _parse_header(blob)
    arrays: "collections.OrderedDict[str, np.ndarray]" = \
        collections.OrderedDict()
    for entry in header["arrays"]:
        n = int(entry["nbytes"])
        if off + n > len(blob):
            raise BlobError(f"truncated array payload {entry['name']!r}")
        try:
            dtype = _wire_dtype(entry["dtype"])
        except TypeError as e:
            raise BlobError(f"unknown wire dtype "
                            f"{entry['dtype']!r}: {e}") from None
        arr = np.frombuffer(blob[off:off + n], dtype=dtype)
        arrays[entry["name"]] = arr.reshape(entry["shape"])
        off += n
    return header["meta"], arrays


def encode_blob(blob: bytes) -> str:
    """Blob -> base64 string for the JSON HTTP surfaces (the router
    relays this string verbatim; only sender and receiver decode)."""
    return base64.b64encode(blob).decode("ascii")


def decode_blob(data: str) -> bytes:
    try:
        return base64.b64decode(data.encode("ascii"), validate=True)
    except Exception as e:
        raise BlobError(f"undecodable blob encoding: {e}") from None


class HostSpillStore:
    """Byte-budgeted host-RAM store of spilled session blobs.

    Restore order is FIFO over spill time (:meth:`oldest`; a failed
    restore re-parks at the FRONT via ``put(front=True)``), and it
    never evicts silently: a parked blob IS a live client's session,
    so when the budget is exhausted :meth:`put` refuses (the would-be
    victim stays resident in HBM, counted
    ``tpushare_migration_refused_total{reason="spill_budget"}`` by the
    caller) instead of destroying an older session.  Thread-safe; the
    serving loop owns all mutation in practice."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._blobs: "collections.OrderedDict[int, bytes]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._blobs)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._blobs.values())

    def put(self, key: int, blob: bytes, front: bool = False) -> bool:
        """Park ``key``'s blob; False when the budget would overflow
        (nothing stored — the caller keeps the session resident).
        ``front=True`` re-parks at the HEAD of the restore order (a
        failed restore keeps its priority instead of going to the
        back of the line)."""
        with self._lock:
            used = sum(len(b) for b in self._blobs.values())
            if used + len(blob) > self.budget_bytes:
                return False
            self._blobs[key] = blob
            if front:
                self._blobs.move_to_end(key, last=False)
            return True

    def take(self, key: int) -> Optional[bytes]:
        """Remove and return ``key``'s blob (None when absent)."""
        with self._lock:
            return self._blobs.pop(key, None)

    def oldest(self) -> Optional[int]:
        """The key parked longest ago (restore-priority order)."""
        with self._lock:
            return next(iter(self._blobs), None)

    def keys(self) -> List[int]:
        with self._lock:
            return list(self._blobs)
