"""Paged-KV continuous batching: block-pooled cache storage for serving.

The dense :class:`~tpushare.serving.continuous.ContinuousBatcher`
reserves ``max_seq`` cache positions per slot, so HBM caps concurrency
at ``pool_bytes / (max_seq row)`` even when requests are short.  Here
the persistent cache is a pool of fixed-size pages
(:func:`tpushare.models.transformer.init_paged_kv`) and each admission
reserves only ``ceil((prompt+max_new)/page)`` pages — mixed-length
traffic packs more in-flight sequences into the same HBM budget.

Design notes (TPU-first):

* all device shapes are static: pool [L, n_pages, Hkv, page, D], page
  table [n_slots, max_seq/page].  Page allocation is host-side control
  logic (a free list), touched only at admit/complete — never per tick;
* reservation is worst-case at admit, so a slot can never starve for a
  page mid-decode (no preemption machinery, the same "static shapes,
  no surprises" rule the rest of the serving plane follows);
* page 0 is the trash page: inactive slots and unowned table entries
  write/read it, the position mask keeps it out of every softmax, and
  the allocator never hands it out — decode math stays bit-identical to
  the dense path (asserted in tests against ``generate()``).

The batcher itself is the dense one with only the four storage hooks
overridden — admission protocol, sampling, and completion bookkeeping
are shared code, so the two paths cannot drift.

Beyond-reference subsystem (the reference is cluster infrastructure
only); the serving counterpart of its HBM binpacking idea applied inside
one process: pages are to the KV pool what GiB fake-devices are to a
chip (pkg/gpu/nvidia/nvidia.go:73-85 fan-out).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import transformer
from . import metrics
from .continuous import (ContinuousBatcher, _Slot, _sample_next,
                         register_jit_entries)

log = logging.getLogger("tpushare.serving")


@functools.partial(jax.jit, static_argnames=("cfg", "prompt_len",
                                             "mesh", "moe"),
                   donate_argnums=(2,))
def _prefill(params, tokens, pools, page_rows, cfg, prompt_len: int,
             mesh=None, adapters=None, aids=None, moe=None):
    return transformer.forward_paged_prefill(
        params, tokens, cfg, pools, page_rows, prompt_len, mesh=mesh,
        adapters=adapters, adapter_ids=aids, moe_mesh=moe)


@functools.partial(jax.jit, static_argnames=("cfg", "window", "mesh",
                                             "moe"),
                   donate_argnums=(2,))
def _prefill_chunk(params, tokens, pools, page_rows, pos, last_idx, cfg,
                   window: int, mesh=None, adapters=None, aids=None,
                   moe=None):
    return transformer.forward_paged_prefill_chunk(
        params, tokens[:, :window], cfg, pools, page_rows, pos, last_idx,
        mesh=mesh, adapters=adapters, adapter_ids=aids, moe_mesh=moe)


def _pp_forward(params, tokens, pools, page_table, lengths, cfg, mesh,
                pp, adapters=None, aids=None, moe=None):
    """Route one paged decode forward: the flat program, or — when
    ``pp = (mesh, n_micro)`` (STATIC, the round-21 pipeline) — the
    microbatched stage wavefront with stage-local pool slabs
    (:func:`transformer.forward_paged_decode_pp`).  ``pp=None`` traces
    byte-identically to the pre-pipeline program.

    Returns (logits, pools, expert_load) like the dense twin: load is
    the per-expert routed-token count of a MoE forward, None for dense
    cfgs AND under the staged pipeline program (the composed stage
    bodies run the ep psum inline, round 24, but the wavefront carry
    discards per-layer load)."""
    if pp is None:
        return transformer.forward_paged_decode(
            params, tokens, cfg, pools, page_table, lengths, mesh=mesh,
            adapters=adapters, adapter_ids=aids, moe_mesh=moe,
            return_expert_load=True)
    pmesh, n_micro = pp
    logits, pools = transformer.forward_paged_decode_pp(
        params, tokens, cfg, pools, page_table, lengths, pmesh,
        n_micro=n_micro, adapters=adapters, adapter_ids=aids,
        moe_mesh=moe)
    return logits, pools, None


@functools.partial(jax.jit, static_argnames=("cfg", "rich", "mesh",
                                             "pp", "moe"),
                   donate_argnums=(2,))
def _tick(params, tokens, pools, page_table, lengths, temps, keys,
          tks, tps, cfg, rich: bool = False, mesh=None, adapters=None,
          aids=None, pp=None, moe=None):
    """Paged twin of continuous._tick (same sampling helper).  ``mesh``
    is STATIC (jax.sharding.Mesh hashes by devices+axes): under tp it
    reaches the paged-attention dispatcher, which shard_maps the Pallas
    read per device."""
    logits, pools, load = _pp_forward(
        params, tokens, pools, page_table, lengths, cfg, mesh, pp,
        adapters=adapters, aids=aids, moe=moe)
    nxt = _sample_next(logits[:, 0], temps, keys,
                       tks if rich else None, tps if rich else None)
    return nxt, pools, load


@functools.partial(jax.jit, static_argnames=("cfg", "n", "rich", "mesh",
                                             "pp", "moe"),
                   donate_argnums=(2,))
def _tick_n(params, tokens, pools, page_table, lengths, temps, keys,
            tks, tps, incs, cfg, n: int, rich: bool = False, mesh=None,
            adapters=None, aids=None, pp=None, moe=None):
    """Paged twin of continuous._tick_n: ``n`` paged decode ticks in one
    device scan.  The page table is FIXED across the chunk — safe because
    reservation is worst-case at admit (a slot can never need a new page
    mid-decode), and a finished slot's surplus steps land on the trash
    page / its own already-released lanes, contained like every other
    garbage write (rewritten before attendable, even across page reuse).

    ``incs`` freezes non-active rows at their aimed garbage position,
    exactly like the dense scan: for full-causal storage the wander was
    merely harmless, but for a sliding-window PAGE RING a wandering
    mid-prefill garbage write at position q would recycle the ring lane
    of q - held*page — still-attendable window content — whenever the
    decode chunk outruns the ring's prefill margin.  Freezing removes
    the coupling between decode_chunk and the ring size entirely.
    """
    return _decode_scan(params, tokens, pools, page_table, lengths,
                        temps, keys, tks, tps, incs, cfg, n, rich, mesh,
                        adapters=adapters, aids=aids, pp=pp, moe=moe)


def _decode_scan(params, tokens, pools, page_table, lengths, temps, keys,
                 tks, tps, incs, cfg, n: int, rich: bool, mesh=None,
                 adapters=None, aids=None, pp=None, moe=None):
    """The paged fused decode scan BODY (trace-level) shared by
    :func:`_tick_n` and the mixed-step program :func:`_tick_mixed` —
    one definition, so the two dispatch flavors cannot drift.

    Returns (toks [B, n], keys, pools, expert_load): the load carry
    exists only when the cfg routes experts AND the flat program runs
    (``track_load`` is a TRACE-time decision, like the dense twin's —
    a None load never changes the carry structure)."""
    track_load = bool(getattr(cfg, "n_experts", 0)) and pp is None

    def body(carry, _):
        tok, pools, lengths, keys, lacc = carry
        ks = jax.vmap(jax.random.split)(keys)
        logits, pools, load = _pp_forward(
            params, tok, pools, page_table, lengths, cfg, mesh, pp,
            adapters=adapters, aids=aids, moe=moe)
        if track_load:
            lacc = lacc + load
        nxt = _sample_next(logits[:, 0], temps, ks[:, 1],
                           tks if rich else None, tps if rich else None)
        return (nxt[:, None], pools, lengths + incs, ks[:, 0], lacc), nxt

    lacc0 = (jnp.zeros((cfg.n_experts,), jnp.float32)
             if track_load else None)
    (_, pools, _, keys, lacc), toks = jax.lax.scan(
        body, (tokens, pools, lengths, keys, lacc0), None, length=n)
    return toks.T, keys, pools, lacc


@functools.partial(jax.jit, static_argnames=("cfg", "chunk_len", "n",
                                             "rich", "mesh", "pp",
                                             "moe"),
                   donate_argnums=(5,))
def _tick_mixed(params, p_tokens, p_tables, p_pos, p_last, pools,
                page_table, tokens, lengths, temps, keys, tks, tps, incs,
                cfg, chunk_len: int, n: int, rich: bool = False,
                mesh=None, adapters=None, aids=None, p_aids=None,
                pp=None, moe=None):
    """Paged twin of continuous._tick_mixed: the coalesced multi-prompt
    prefill (:func:`transformer.forward_paged_prefill_batch` — live rows
    write their own distinct pages, padded rows ride all-zero tables so
    every write lands on the masked TRASH page) followed by the fused
    ``n``-step paged decode scan, in ONE dispatch.  The page table is
    FIXED across the whole round, as _tick_n requires — the prefill
    writes through each row's own table row, never reshaping it."""
    sel, pools = transformer.forward_paged_prefill_batch(
        params, p_tokens[:, :chunk_len], cfg, pools, p_tables, p_pos,
        p_last, mesh=mesh, adapters=adapters, adapter_ids=p_aids,
        moe_mesh=moe)
    # load covers the decode scan only (the prefill block's routing is
    # not sampled on the paged path — the decode phase dominates the
    # balance signal and the dense twin's histogram carries the mixed
    # prefill contribution)
    toks, keys, pools, load = _decode_scan(
        params, tokens, pools, page_table, lengths, temps, keys, tks,
        tps, incs, cfg, n, rich, mesh, adapters=adapters, aids=aids,
        pp=pp, moe=moe)
    return sel, toks, keys, pools, load


@functools.partial(jax.jit, static_argnames=("cfg", "k", "ngram",
                                             "n_rounds", "rich", "mesh",
                                             "moe"),
                   donate_argnums=(2,))
def _tick_spec(params, bufs, pools, page_table, buf_lens, n_ctxs,
               next_toks, remainings, actives, temps, keys, tks, tps,
               cfg, k: int, ngram: int, n_rounds: int,
               rich: bool = False, mesh=None, adapters=None, aids=None,
               moe=None):
    """Paged twin of continuous._tick_spec: ``n_rounds`` of batched
    prompt-lookup speculation against the page pool in one dispatch
    (the shared round body, :func:`tpushare.serving.speculative
    .spec_scan`, with the verify forward swapped for
    :func:`transformer.forward_paged_verify`).  The page table is
    FIXED across the whole batch, as every paged scan requires: the
    verify scatter walks each row's OWN reserved pages (up to
    ``ceil(k/page)+1`` per round), overflow/rejected tails land on the
    masked trash page or on positions a later block rewrites — see
    forward_paged_verify on the containment, and
    ``PagedContinuousBatcher.spec_fallback_reason`` for the one
    structural gate (a windowed page ring's eviction margin must cover
    ``k``)."""
    from .speculative import spec_scan

    def verify(blocks, n_ctxs, live, pools):
        return transformer.forward_paged_verify(
            params, blocks, cfg, pools, page_table, n_ctxs, mesh=mesh,
            adapters=adapters, adapter_ids=aids, moe_mesh=moe)

    return spec_scan(verify, _sample_next, bufs, buf_lens, n_ctxs,
                     next_toks, remainings, actives, temps, keys, tks,
                     tps, pools, k, ngram, n_rounds, rich)


@functools.partial(jax.jit, static_argnames=("cfg", "chunk_len", "k",
                                             "ngram", "n_rounds", "rich",
                                             "mesh", "moe"),
                   donate_argnums=(5,))
def _tick_mixed_spec(params, p_tokens, p_tables, p_pos, p_last, pools,
                     page_table, bufs, buf_lens, n_ctxs, next_toks,
                     remainings, actives, temps, keys, tks, tps, cfg,
                     chunk_len: int, k: int, ngram: int, n_rounds: int,
                     rich: bool = False, mesh=None, adapters=None,
                     aids=None, p_aids=None, moe=None):
    """Paged twin of continuous._tick_mixed_spec: the coalesced
    multi-prompt prefill (:func:`transformer.forward_paged_prefill_
    batch`) followed by the speculative verify rounds, in ONE dispatch
    — the mixed step with speculation as its third co-resident phase.
    Mid-prefill rows ride the spec scan frozen (inactive), their
    (1+k)-wide garbage verify aimed at the post-chunk offset exactly
    like the plain mixed scan's ``incs``-frozen rows."""
    sel, pools = transformer.forward_paged_prefill_batch(
        params, p_tokens[:, :chunk_len], cfg, pools, p_tables, p_pos,
        p_last, mesh=mesh, adapters=adapters, adapter_ids=p_aids,
        moe_mesh=moe)

    from .speculative import spec_scan

    def verify(blocks, n_ctxs, live, pools):
        return transformer.forward_paged_verify(
            params, blocks, cfg, pools, page_table, n_ctxs, mesh=mesh,
            adapters=adapters, adapter_ids=aids, moe_mesh=moe)

    out = spec_scan(verify, _sample_next, bufs, buf_lens, n_ctxs,
                    next_toks, remainings, actives, temps, keys, tks,
                    tps, pools, k, ngram, n_rounds, rich)
    return (sel,) + out


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_pages(pools, ids, blocks):
    """Write ``blocks`` (pytree matching ``pools`` with page axis
    ``len(ids)``) into the pool pages ``ids`` — the import half of
    session migration.  The pool is DONATED so XLA updates it in place
    instead of holding two full copies across the import (each distinct
    page count compiles once, like the fused n_steps programs)."""
    return jax.tree_util.tree_map(
        lambda pool, blk: pool.at[:, ids].set(blk), pools, blocks)


# every paged jitted program joins the retrace watch list (and the
# dispatch auditor's registry cross-check): before round 18 the
# retrace counter saw only the DENSE programs, so steady cache growth
# on a paged service was invisible to tpushare_jit_retraces_total
register_jit_entries(_prefill, _prefill_chunk, _tick, _tick_n,
                     _tick_mixed, _tick_spec, _tick_mixed_spec,
                     _scatter_pages)


def _store_arrays(prefix: str, store) -> list:
    """(name, leaf) pairs of one K or V store under the migration wire
    naming: a bf16 store is one ``k``/``v`` array, an int8 store ships
    its values and scales as ``k.q``/``k.s`` (etc.)."""
    if isinstance(store, dict):
        return [(f"{prefix}.q", store["q"]), (f"{prefix}.s", store["s"])]
    return [(prefix, store)]


@dataclasses.dataclass
class _CachedPrefix:
    """A registered prompt prefix whose K/V pages live in the pool.

    ``pages`` are REGISTRY-owned (not any slot's): admitted requests
    map them read-only into their page tables and bump ``active``;
    nothing ever writes a registered page (decode/prefill writes start
    past the shared region, garbage writes are aimed at each slot's own
    positions).  Evictable only at active == 0.

    ``adapter`` names the LoRA adapter the donor request ran with
    (None = base model): cached K/V depends on the donor's wk/wv
    adapter deltas, so a prefix is reusable ONLY by requests running
    the SAME adapter — the registry keys and the lookup both carry it
    (cross-adapter reuse would serve adapter-tainted keys and break
    the mixed-batch == solo exactness contract).
    """

    tokens: tuple          # the full-page prefix, exactly
    pages: list            # physical pages, in position order
    active: int = 0        # slots currently mapping these pages
    last_used: float = 0.0
    adapter: Optional[str] = None


class PagedContinuousBatcher(ContinuousBatcher):
    """Dense batcher with the storage hooks swapped for a paged pool."""

    def __init__(self, params, cfg: transformer.ModelConfig, n_slots: int,
                 page_size: int = 16, n_pages: Optional[int] = None,
                 mesh=None, max_prefill_chunk: int = 64,
                 prefix_cache: bool = False,
                 pool_bytes: Optional[int] = None,
                 spec_k: int = 0, adapter_slots: int = 0,
                 adapter_rank: int = 8, adapter_loader=None,
                 pp: int = 1, pp_microbatches: Optional[int] = None):
        if cfg.max_seq % page_size:
            raise ValueError("max_seq must be a multiple of page_size")
        self.page_size = page_size
        self.pages_per_slot = cfg.max_seq // page_size
        # POSITION STRIPING (round 17): a mesh with a >1 "sp" axis
        # round-robins each sequence's logical page ranges over the
        # position shards (range j -> stripe j % sp) and shards the
        # pool's page axis, so ONE sequence's KV pages — and its max
        # context — span the whole mesh instead of one shard's pool.
        from ..ops.attention import tp_degree
        self.sp_shards = tp_degree(mesh, "sp")
        if self.sp_shards > 1 and transformer.wants_rolling(cfg):
            # the windowed page RING recycles pages in place; striping
            # its eviction arithmetic across shards buys nothing (the
            # ring is already O(window)) and would entangle the margin
            # logic — refuse loudly instead of serving a subtle alias
            raise ValueError(
                "position striping (sp mesh axis) requires a "
                "full-causal config — the windowed page ring recycles "
                "pages in place")
        if pool_bytes is not None:
            # size the pool by an HBM BUDGET instead of a page count:
            # the same byte grant buys ~2x the pages under kv_dtype=int8
            # (one dtype-aware byte model — ops.quant.kv_cache_bytes)
            if n_pages is not None:
                raise ValueError("pass n_pages or pool_bytes, not both")
            from ..ops.quant import kv_cache_bytes
            n_pages = int(pool_bytes) // kv_cache_bytes(cfg, page_size)
            if self.sp_shards > 1:
                # a byte budget rounds DOWN to equal stripes (never
                # exceed the grant); a budget too small for one usable
                # page per stripe raises below like any tiny pool
                n_pages = (n_pages // self.sp_shards) * self.sp_shards
        # Upper bound on any prefill chunk through this batcher —
        # admission clamps to it.  Sized into the windowed page ring
        # (see _held_pages); irrelevant for full-causal requests.
        self.max_prefill_chunk = max(
            page_size,
            -(-max_prefill_chunk // page_size) * page_size)
        # PREFIX CACHE (vLLM-style, full-causal only): completed
        # requests donate their prompt's full pages to a registry;
        # later requests whose prompt starts with a registered prefix
        # map those pages read-only into their table and prefill only
        # the remainder.  Exact by construction — a position's K/V
        # depends only on its causal prefix, so same-prefix K/V is the
        # same K/V.  A windowed page RING recycles pages in place, so
        # the two features are mutually exclusive.
        if prefix_cache and transformer.wants_rolling(cfg):
            raise ValueError("prefix_cache requires a full-causal config "
                             "(the windowed page ring recycles pages)")
        self.prefix_cache_enabled = bool(prefix_cache)
        self._prefixes: Dict[tuple, _CachedPrefix] = {}
        self._slot_prefix: Dict[int, tuple] = {}   # slot -> registry key
        self._slot_shared: Dict[int, int] = {}     # slot -> shared tokens
        #: registry HBM budget: at most this many pages parked on
        #: cached prefixes (evicted LRU at zero active when needed)
        self.max_cached_pages = self.pages_per_slot * 2
        # Default pool: every slot can hold a full max_seq sequence (the
        # dense equivalent + 1 trash page). Pass a smaller n_pages to
        # overcommit slots against the real traffic mix — the point.
        self.n_pages = (n_pages if n_pages is not None
                        else n_slots * self.pages_per_slot
                        + self.sp_shards)
        if self.sp_shards > 1:
            # equal stripes: every shard holds n_pages/sp pages with
            # its own local trash page (global s*per) — an explicit
            # n_pages rounds UP so no stripe comes up short of what
            # the caller asked for
            sp = self.sp_shards
            self.n_pages = -(-self.n_pages // sp) * sp
            if self.n_pages < 2 * sp:
                raise ValueError("need at least one non-trash page "
                                 "per position stripe")
        elif self.n_pages < 2:
            raise ValueError("need at least one non-trash page")
        # paged storage is position-indexed (no ring wraparound); the
        # rolling-slot layout is a dense-pool concern
        super().__init__(params, cfg, n_slots, mesh=mesh,
                         rolling_slots=False, spec_k=spec_k,
                         adapter_slots=adapter_slots,
                         adapter_rank=adapter_rank,
                         adapter_loader=adapter_loader,
                         pp=pp, pp_microbatches=pp_microbatches)

    def _pp_rolling_storage(self, cfg) -> bool:
        # the windowed page RING recycles pages in place — same
        # structural refusal as the dense rolling pool (pp_storage)
        return transformer.wants_rolling(cfg)

    def validate_request(self, prompt: List[int],
                         max_new_tokens: int) -> None:
        super().validate_request(prompt, max_new_tokens)
        need = self._held_pages(len(prompt), max_new_tokens)
        sp = self.sp_shards
        if sp > 1:
            # capacity is PER STRIPE: range j draws from stripe j % sp,
            # each stripe holding n_pages/sp - 1 usable pages (its
            # local trash is never allocatable) — a request fits iff
            # every stripe can carry its share of the ranges
            usable = self.n_pages // sp - 1
            worst = -(-need // sp)          # stripe 0 carries the ceil
            if worst > usable:
                raise ValueError(
                    f"request needs {worst} pages on a position stripe "
                    f"but each of the {sp} stripes holds only {usable} "
                    f"usable pages")
            return
        if need > self.n_pages - 1:     # page 0 is never allocatable
            raise ValueError(
                f"request needs {need} pages but the pool holds only "
                f"{self.n_pages - 1} usable pages")

    # -- speculation capability ----------------------------------------
    def spec_fallback_reason(self, k: int) -> Optional[str]:
        """Paged pools verify k-token blocks without extra reservation
        (rejected tails land past the committed length on the slot's
        own pages — position-masked until rewritten — or past the
        reservation on the trash page), EXCEPT the windowed page RING:
        its verify writes recycle pages in place, so the ring's margin
        beyond the window (the SAME held-page count the allocation
        uses, :meth:`_ring_held_pages`) must also cover ``k`` — an
        eviction at written position q must only reach positions
        <= q - window.  Shorter margins refuse speculation structurally
        ("ring_margin"); everything else is capable."""
        if transformer.wants_rolling(self.cfg):
            margin = (self._ring_held_pages() * self.page_size
                      - self.cfg.window)
            if k > margin:
                return "ring_margin"
        return None

    def _spec_needs_headroom(self) -> bool:
        """Never: the page-table walk routes past-the-end writes to the
        trash page instead of clamping onto real positions (see
        transformer.forward_paged_verify)."""
        return False

    def storage_info(self) -> dict:
        """HBM accounting for the page pool (vs the base class's
        per-slot rows): persistent KV cost is pages, not slots.  Byte
        math goes through :func:`tpushare.ops.quant.kv_cache_bytes`, so
        an int8 pool prices its pages (and the ``pool_bytes`` sizing
        knob admits ~2x of them) with the same model the gauges and
        ``/usage`` reporting use."""
        from ..ops.attention import (paged_kernel_fallback_reason,
                                     spec_verify_rows, tp_degree)
        from ..ops.quant import kv_cache_bytes
        cfg = self.cfg
        bytes_per_page = kv_cache_bytes(cfg, self.page_size)
        # the EFFECTIVE read path, not the configured one: a pallas
        # config whose pool cannot lower on Mosaic (page below the
        # dtype's sublane tile, lane-unaligned head_dim), whose head
        # counts a tp mesh cannot split into whole GQA groups per
        # shard, whose page count an sp mesh cannot split into equal
        # stripes, or a forced reference escape hatch runs the XLA
        # gather — telemetry must say so, or an operator debugging HBM
        # pressure / a flat speedup reads "pallas, transient 0" while
        # every tick pays the dense gather.  A spec-provisioned pool
        # prices the VERIFY read's q-row block (rows = n_rep * (1+k),
        # the spec row multiplier) — its steady-state reads are k+1
        # rows wide, not 1
        rows = (spec_verify_rows(cfg.n_heads, cfg.n_kv_heads,
                                 self.spec_k) if self.spec_k else 1)
        sp = self.sp_shards
        kernel = cfg.attn_kernel
        reason = None
        if kernel == "pallas":
            reason = paged_kernel_fallback_reason(
                self.page_size, cfg.head_dim,
                transformer.kv_quantized(cfg), cfg.dtype, rows=rows,
                tp=tp_degree(self.mesh), n_kv_heads=cfg.n_kv_heads,
                n_heads=cfg.n_heads, sp=sp, n_pages=self.n_pages)
            if reason is not None:
                kernel = "xla"
        pool_bytes = int(bytes_per_page * self.n_pages)
        info = {"kind": "paged", "kv_dtype": cfg.kv_dtype,
                # the attention READ path + what the XLA gather's dense
                # per-layer transient peaks at (0 under the Pallas
                # kernel — the saving the kernel exists for; see
                # transformer.paged_read_transient_bytes)
                "attn_kernel": kernel,
                # WHY a configured pallas kernel degrades (None/absent
                # when clean) — what llm.py logs once at service start
                # so a silent page_tile/head_dim/sp_pool demotion is an
                # operator-visible fact, not a buried "(fb N)"
                "attn_fallback_reason": reason,
                "attn_read_transient_bytes":
                    transformer.paged_read_transient_bytes(
                        cfg, self.n_slots, attn_kernel=kernel),
                "page_tokens": self.page_size,
                "bytes_per_page": int(bytes_per_page),
                "n_pages": self.n_pages,
                "pool_bytes": pool_bytes,
                # position striping (round 17): shards one sequence's
                # pages span, and what each shard persistently holds
                "sp_shards": sp,
                "pool_bytes_per_shard": pool_bytes // sp}
        info.update(self._pp_storage_info(pool_bytes))
        if sp > 1:
            # what the cross-shard merge moves per striped KERNEL
            # dispatch per layer: each shard contributes its f32
            # (out, max, sumexp) partial 3-tuple over `rows` query
            # rows per slot — head_dim + 2 stat lanes of f32 per
            # (slot, head).  The striped GATHER path instead
            # all-gathers the dense view, which is exactly
            # attn_read_transient_bytes (now crossing the interconnect
            # rather than staying HBM-local)
            # rows = n_rep * (1 + spec_k) per kv head, so a slot's
            # query rows total n_kv_heads * rows
            info["sp_merge_transient_bytes"] = int(
                self.n_slots * cfg.n_kv_heads * rows
                * (cfg.head_dim + 2) * 4)
        if self.adapter_pool is not None:
            # the SECOND HBM pool class (round 20): adapter residency
            # economics next to the KV pool's
            info.update(self.adapter_pool.storage_info())
        info.update(self._expert_storage_info())
        return info

    # -- storage hooks -------------------------------------------------
    def _init_storage(self) -> None:
        self.pools = transformer.init_paged_kv(
            self.cfg, self.n_pages, self.page_size)
        if self.mesh is not None:
            from ..parallel.mesh import shard_kv_storage
            self.pools = shard_kv_storage(
                self.pools, self.mesh, page_axis="sp",
                layer_axis=("pp" if "pp" in self.mesh.axis_names
                            else None))
        self.page_table = np.zeros(
            (self.n_slots, self.pages_per_slot), np.int32)
        # Free pages, one list per position stripe.  Unstriped (sp==1)
        # this is one list and page 0 the one trash page — byte-for-
        # byte the old layout.  Striped, stripe s owns global pages
        # [s*per, (s+1)*per) and its local page 0 (global s*per) is
        # that stripe's TRASH page: striped_local_view maps global 0
        # (the 0-padded table convention) onto it per shard, and the
        # allocator never hands any of them out.
        per = self.n_pages // self.sp_shards
        self._pages_per_stripe = per
        self._free_by_stripe: List[List[int]] = [
            list(range(s * per + 1, (s + 1) * per))
            for s in range(self.sp_shards)]
        self._slot_pages: Dict[int, List[int]] = {}
        self._update_page_gauges()

    # -- striped free-list helpers -------------------------------------
    # (sp == 1 degenerates to one list; every mutation routes through
    # these so the stripe invariant — range j's page on stripe j % sp —
    # cannot be violated by one forgotten call site)
    def _stripe_of_page(self, p: int) -> int:
        return int(p) // self._pages_per_stripe

    def _free_pages_return(self, pages) -> None:
        for p in pages:
            self._free_by_stripe[self._stripe_of_page(p)].append(int(p))

    def free_page_count(self) -> int:
        return sum(len(s) for s in self._free_by_stripe)

    def _stripe_need(self, ranges) -> List[int]:
        need = [0] * self.sp_shards
        for j in ranges:
            need[j % self.sp_shards] += 1
        return need

    def _stripes_short(self, need: List[int]) -> bool:
        return any(n > len(self._free_by_stripe[s])
                   for s, n in enumerate(need))

    def _update_page_gauges(self) -> None:
        """KV-pool utilization for /metrics (trash pages — one per
        stripe, page 0 alone unstriped — excluded: never allocatable,
        so used+free == n_pages - sp_shards)."""
        free = self.free_page_count()
        metrics.KV_PAGES_FREE.set(free)
        metrics.KV_PAGES_USED.set(self.n_pages - self.sp_shards - free)

    def _held_pages(self, prompt_len: int, max_new: int) -> int:
        """Physical pages a request occupies SIMULTANEOUSLY.

        Full-causal: every page of the sequence (the whole history is
        attendable).  Sliding-window: a RING of
        ``ceil(window/page) + ceil(max_prefill_chunk/page) + 1`` pages.
        The ring must cover the window PLUS one whole prefill chunk,
        because a chunk's page walk writes every chunk page BEFORE its
        attention runs: a write at position p evicts position
        p - held*page, and the chunk's earliest query (at the chunk
        start) is entitled to the window behind it — the chunk-sized
        margin keeps every in-dispatch eviction strictly older than
        that.  Decode writes are one token per scan step (earlier
        queries already attended), so they need no margin; the window
        mask (already applied by the paged attention) keeps recycled
        pages' aliased old-range claims out of every softmax.
        """
        n_ranges = -(-(prompt_len + max_new) // self.page_size)
        if transformer.wants_rolling(self.cfg):
            return min(n_ranges, self._ring_held_pages())
        return n_ranges

    def _ring_held_pages(self) -> int:
        """THE windowed page ring's size in pages (window + one whole
        prefill chunk + 1; see :meth:`_held_pages` on why the chunk
        margin exists) — one definition shared by the allocation
        (:meth:`_held_pages`) and the speculation eviction-margin gate
        (:meth:`spec_fallback_reason`), so the safety check can never
        drift from what was actually allocated."""
        w_pages = -(-self.cfg.window // self.page_size)
        c_pages = -(-self.max_prefill_chunk // self.page_size)
        return w_pages + c_pages + 1

    @staticmethod
    def _registry_key(adapter: Optional[str], tokens: tuple):
        """Prefix-registry key: the token tuple for base requests
        (byte-identical to the pre-adapter registry), namespaced by the
        adapter name otherwise — same tokens under different adapters
        are DIFFERENT cached K/V."""
        return tokens if adapter is None else (adapter, tokens)

    def _lookup_prefix(self, prompt: List[int],
                       adapter: Optional[str] = None
                       ) -> Optional[_CachedPrefix]:
        """Longest registered prefix usable for this prompt UNDER THIS
        ADAPTER: a full-page token prefix, capped one token short of
        the prompt (admission must still prefill >= 1 position to
        produce the first logits); entries donated under a different
        adapter never match (their K/V carries that adapter's
        deltas)."""
        if not self.prefix_cache_enabled or prompt is None:
            return None
        usable = ((len(prompt) - 1) // self.page_size) * self.page_size
        best = None
        for entry in self._prefixes.values():
            n = len(entry.tokens)
            if (entry.adapter == adapter and n <= usable
                    and tuple(prompt[:n]) == entry.tokens
                    and (best is None or n > len(best.tokens))):
                best = entry
        return best

    def _evict_prefixes(self, need_pages,
                        registry_room: int = 0) -> None:
        """Free LRU zero-active cached prefixes until ``need_pages``
        free pages exist AND ``registry_room`` more cached pages would
        fit the budget (or nothing evictable remains).  Entries with
        active mappings are never victims — a matched prefix must bump
        ``active`` BEFORE any eviction runs, or it could evict itself
        and alias its pages.  ``need_pages`` is a total count, or a
        PER-STRIPE list on a striped pool (the binding constraint
        there; a victim's pages relieve whichever stripes they live
        on)."""
        def _short():
            if isinstance(need_pages, (list, tuple)):
                return self._stripes_short(list(need_pages))
            return self.free_page_count() < need_pages

        def _over():
            cached = sum(len(e.pages) for e in self._prefixes.values())
            return (_short()
                    or cached + registry_room > self.max_cached_pages)

        while _over():
            idle = [e for e in self._prefixes.values() if e.active == 0]
            if not idle:
                return
            victim = min(idle, key=lambda e: e.last_used)
            del self._prefixes[self._registry_key(victim.adapter,
                                                  victim.tokens)]
            self._free_pages_return(victim.pages)

    def _reserve(self, slot: int, prompt_len: int, max_new: int,
                 prompt: Optional[List[int]] = None) -> bool:
        n_ranges = -(-(prompt_len + max_new) // self.page_size)
        held = self._held_pages(prompt_len, max_new)
        # the slot's adapter is pinned (and mapped) BEFORE _reserve, so
        # the prefix lookup matches only same-adapter donations
        ad_name = self._adapter_name_of(slot)
        shared = (self._lookup_prefix(prompt, ad_name)
                  if held == n_ranges else None)
        n_shared = len(shared.pages) if shared is not None else 0
        if shared is not None:
            # claim BEFORE any eviction: an idle matched entry must not
            # be its own eviction victim (pages would alias)
            shared.active += 1
            shared.last_used = time.monotonic()
        own = held - n_shared
        # STRIPE-AWARE need: range j draws from stripe j % sp (the
        # round-robin the striped read reconstructs); unstriped this
        # is one stripe and the old total-count check
        own_ranges = (list(range(n_shared, n_ranges))
                      if held == n_ranges else list(range(own)))
        need = self._stripe_need(own_ranges)
        if self._stripes_short(need):
            self._evict_prefixes(need)
        if self._stripes_short(need):
            if shared is not None:
                shared.active -= 1      # claim rolled back
            return False                # page backpressure
        self.page_table[slot, :] = 0
        pages: List[int] = []
        if shared is not None:
            # read-only mapping of the registry's pages over the shared
            # prefix (the donor allocated them stripe-aligned, so range
            # j's page already lives on stripe j % sp); this slot's own
            # pages take over from there
            self.page_table[slot, :n_shared] = shared.pages
            self._slot_prefix[slot] = self._registry_key(shared.adapter,
                                                         shared.tokens)
            self._slot_shared[slot] = n_shared * self.page_size
            for j in range(n_shared, n_ranges):
                p = self._free_by_stripe[j % self.sp_shards].pop()
                self.page_table[slot, j] = p
                pages.append(p)
        elif held == n_ranges:
            # full-causal identity layout, one page per range, each
            # from its stripe (sp == 1: the single free list, the old
            # pop order)
            for j in range(n_ranges):
                p = self._free_by_stripe[j % self.sp_shards].pop()
                self.page_table[slot, j] = p
                pages.append(p)
        else:
            # STATIC ring mapping: position range j -> pages[j % held]
            # (windowed page ring; never striped — __init__ refuses).
            # No mid-decode table updates, ever — the fixed-table
            # invariant _tick_n depends on holds by construction.
            pages = [self._free_by_stripe[0].pop() for _ in range(own)]
            for j in range(n_ranges):
                self.page_table[slot, j] = pages[j % held]
        self._slot_pages[slot] = pages
        self._update_page_gauges()
        if self.prefix_cache_enabled and prompt is not None \
                and held == n_ranges:
            # counted only on SUCCESSFUL reservation: a backpressure
            # failure gets requeued and retried every loop iteration,
            # which would inflate the hit-rate counters once per tick
            # for the whole pressure window
            (metrics.PREFIX_HITS if shared is not None
             else metrics.PREFIX_MISSES).inc()
        return True

    def _prefill_start(self, slot: int) -> int:
        return self._slot_shared.get(slot, 0)

    def _release(self, slot: int) -> None:
        key = self._slot_prefix.pop(slot, None)
        self._slot_shared.pop(slot, None)
        if key is not None:
            entry = self._prefixes.get(key)
            if entry is not None:
                entry.active -= 1
                entry.last_used = time.monotonic()
        elif self.prefix_cache_enabled:
            self._maybe_register(slot)
        # adapter unpin LAST: _maybe_register reads the slot's adapter
        # name to namespace its donation
        self._release_adapter(slot)
        self.page_table[slot, :] = 0
        self._free_pages_return(self._slot_pages.pop(slot, []))
        self._update_page_gauges()

    def _maybe_register(self, slot: int) -> None:
        """Donate a COMPLETED request's pure-prompt full pages to the
        prefix registry (instead of freeing them), so the next
        same-prefix request skips their prefill.

        Only decoding slots register (a cancelled mid-prefill slot's
        pages are part-garbage), only prefixes not already registered,
        and only pages holding PROMPT positions exclusively — the page
        containing prompt_len onward has decode writes.  Slots that
        themselves mapped a cached prefix just decref (the registry
        keeps the canonical pages); extension registration is future
        work.
        """
        s = self.slots.get(slot)
        if s is None or s.prompt_len <= 1:
            return
        k_pure = s.prompt_len // self.page_size     # whole-prompt pages
        if k_pure < 1:
            return
        tokens = tuple(s.output[:k_pure * self.page_size])
        ad_name = self._adapter_name_of(slot)
        key = self._registry_key(ad_name, tokens)
        if key in self._prefixes:
            return
        self._evict_prefixes(0, registry_room=k_pure)
        cached_now = sum(len(e.pages) for e in self._prefixes.values())
        if cached_now + k_pure > self.max_cached_pages:
            return                      # nothing idle to evict
        own = self._slot_pages.get(slot, [])
        # full-causal identity layout: table row j == own[j]
        donated = [int(p) for p in self.page_table[slot, :k_pure]]
        if any(p == 0 for p in donated) or len(own) < k_pure:
            return                      # defensive: never donate trash
        self._prefixes[key] = _CachedPrefix(
            tokens=tokens, pages=donated, active=0,
            last_used=time.monotonic(), adapter=ad_name)
        self._slot_pages[slot] = [p for p in own if p not in set(donated)]

    def _prefill_into(self, slot: int, tokens, prompt_len: int):
        span = len(self._slot_pages.get(slot, ())) * self.page_size
        start = self._prefill_start(slot)
        if start or (transformer.wants_rolling(self.cfg) and span
                     and prompt_len > span):
            # Stream through max_prefill_chunk-sized page-aligned
            # chunks (the bit-exact chunk body chunked admission uses)
            # when either (a) a cached PREFIX covers the prompt's head —
            # the whole-prompt page walk would rewrite registry-owned
            # pages other slots are mapping — or (b) a whole-prompt walk
            # would alias the windowed page ring.
            row = np.asarray(tokens).reshape(-1)[:prompt_len]
            step = self.max_prefill_chunk
            pos, logits_v = start, None
            while pos < prompt_len:
                # FIXED window width (advance_prefill's compile-count
                # discipline: widths stay in {step, max_seq - pos}, so a
                # short final piece never keys a fresh XLA program)
                window = min(step, self.cfg.max_seq - pos)
                piece = row[pos:pos + window]
                padded = np.zeros((1, window), np.int32)
                padded[0, :len(piece)] = piece
                logits_v = self._prefill_chunk_into(
                    slot, padded, pos, len(piece) - 1, window)
                pos += len(piece)
            return logits_v
        adapters, aids = self._adapter_operands(
            [self._slot_adapter.get(slot, 0)])
        logits, self.pools = _prefill(
            self.params, tokens, self.pools,
            jnp.asarray(self.page_table[slot]), self.cfg, prompt_len,
            mesh=self.mesh, adapters=adapters, aids=aids,
            moe=self._expert_operands())
        return logits[0]      # [V]: the prompt's last-position logits

    def _step(self, tokens, lengths, temps, keys, tks, tps, rich,
              ads=None):
        adapters, aids = self._adapter_operands(ads)
        nxt, self.pools, self._moe_load = _tick(
            self.params, tokens, self.pools, jnp.asarray(self.page_table),
            lengths, temps, keys, tks, tps, self.cfg, rich,
            mesh=self.mesh, adapters=adapters, aids=aids,
            pp=self._pp_args, moe=self._expert_operands())
        return nxt

    def _step_n(self, tokens, lengths, temps, keys, tks, tps, incs, rich,
                n_steps: int, ads=None):
        adapters, aids = self._adapter_operands(ads)
        toks, keys, self.pools, self._moe_load = _tick_n(
            self.params, tokens, self.pools, jnp.asarray(self.page_table),
            lengths, temps, keys, tks, tps, incs, self.cfg, n_steps, rich,
            mesh=self.mesh, adapters=adapters, aids=aids,
            pp=self._pp_args, moe=self._expert_operands())
        return toks, keys

    def _prefill_chunk_into(self, slot: int, padded_tokens, pos: int,
                            last_idx: int, chunk_len: int):
        adapters, aids = self._adapter_operands(
            [self._slot_adapter.get(slot, 0)])
        logits, self.pools = _prefill_chunk(
            self.params, jnp.asarray(padded_tokens), self.pools,
            jnp.asarray(self.page_table[slot]), pos, last_idx, self.cfg,
            chunk_len, mesh=self.mesh, adapters=adapters, aids=aids,
            moe=self._expert_operands())
        return logits

    def _mixed_chunk_len(self, chunk: int) -> int:
        """Mixed-round window width on paged storage: rounded UP to a
        page multiple (writes are whole pages) and clamped into the
        windowed page ring's prefill margin (see _held_pages) — the same
        rounding admit_chunked applies to sequential chunks."""
        c = -(-max(1, chunk) // self.page_size) * self.page_size
        if transformer.wants_rolling(self.cfg):
            c = min(c, self.max_prefill_chunk)
        return max(self.page_size, c)

    def _step_mixed(self, p_tokens, p_slots, p_active, p_pos, p_last,
                    tokens, lengths, temps, keys, tks, tps, incs, rich,
                    chunk_len: int, n_steps: int, ads=None, p_ads=None):
        p_tables = self._prefill_tables(p_slots, p_active)
        adapters, aids = self._adapter_operands(ads)
        _, p_aids = self._adapter_operands(p_ads)
        sel, toks, keys, self.pools, self._moe_load = _tick_mixed(
            self.params, jnp.asarray(p_tokens), jnp.asarray(p_tables),
            jnp.asarray(p_pos), jnp.asarray(p_last), self.pools,
            jnp.asarray(self.page_table), tokens, lengths, temps, keys,
            tks, tps, incs, self.cfg, chunk_len, n_steps, rich,
            mesh=self.mesh, adapters=adapters, aids=aids, p_aids=p_aids,
            pp=self._pp_args, moe=self._expert_operands())
        return sel, toks, keys

    def _prefill_tables(self, p_slots, p_active):
        """Per-row page-table rows for a coalesced prefill block (live
        rows get their slot's table; padded rows all-zero tables onto
        the masked trash page) — shared by both paged mixed hooks."""
        p_tables = np.zeros((len(p_slots), self.pages_per_slot), np.int32)
        for r in range(len(p_slots)):
            if p_active[r]:
                p_tables[r] = self.page_table[p_slots[r]]
        return p_tables

    def _step_spec(self, bufs, buf_lens, n_ctxs, next_toks, remainings,
                   actives, temps, keys, tks, tps, rich, k: int,
                   ngram: int, n_rounds: int, ads=None):
        adapters, aids = self._adapter_operands(ads)
        (bufs, _, _, next_toks, produced, keys, accepts, lives,
         self.pools) = _tick_spec(
            self.params, bufs, self.pools, jnp.asarray(self.page_table),
            buf_lens, n_ctxs, next_toks, remainings, actives, temps,
            keys, tks, tps, self.cfg, k, ngram, n_rounds, rich,
            mesh=self.mesh, adapters=adapters, aids=aids,
            moe=self._expert_operands())
        return bufs, produced, next_toks, keys, accepts, lives

    def _step_mixed_spec(self, p_tokens, p_slots, p_active, p_pos,
                         p_last, bufs, buf_lens, n_ctxs, next_toks,
                         remainings, actives, temps, keys, tks, tps,
                         rich, chunk_len: int, k: int, ngram: int,
                         n_rounds: int, ads=None, p_ads=None):
        p_tables = self._prefill_tables(p_slots, p_active)
        adapters, aids = self._adapter_operands(ads)
        _, p_aids = self._adapter_operands(p_ads)
        (sel, bufs, _, _, next_toks, produced, keys, accepts, lives,
         self.pools) = _tick_mixed_spec(
            self.params, jnp.asarray(p_tokens), jnp.asarray(p_tables),
            jnp.asarray(p_pos), jnp.asarray(p_last), self.pools,
            jnp.asarray(self.page_table), bufs, buf_lens, n_ctxs,
            next_toks, remainings, actives, temps, keys, tks, tps,
            self.cfg, chunk_len, k, ngram, n_rounds, rich,
            mesh=self.mesh, adapters=adapters, aids=aids, p_aids=p_aids,
            moe=self._expert_operands())
        return sel, bufs, produced, next_toks, keys, accepts, lives

    # ------------------------------------------------------------------
    def admit_chunked(self, prompt, max_new_tokens, temperature: float = 0.0,
                      seed: int = 0, chunk: int = 64, eos_id=None,
                      top_k: int = 0, top_p: float = 1.0, adapter=None,
                      trace=None):
        """Chunked admission with the window rounded UP to a page
        multiple: paged writes are page-aligned (pos stays a multiple of
        the window, the window a multiple of the page — max_seq is a
        page multiple too, so the max_seq clamp preserves alignment).
        Invalid chunks (< 1) raise in the base class, keeping the two
        admission paths' validation identical."""
        if chunk >= 1:
            chunk = -(-chunk // self.page_size) * self.page_size
            # the windowed page ring is sized for chunks up to
            # max_prefill_chunk (see _held_pages) — larger ones would
            # evict window content their own earlier queries attend
            if transformer.wants_rolling(self.cfg):
                chunk = min(chunk, self.max_prefill_chunk)
        return super().admit_chunked(prompt, max_new_tokens,
                                     temperature=temperature, seed=seed,
                                     chunk=chunk, eos_id=eos_id,
                                     top_k=top_k, top_p=top_p,
                                     adapter=adapter, trace=trace)

    # -- session migration (export / import / release) -----------------
    def can_migrate(self) -> bool:
        return True

    def export_session(self, rid: int) -> bytes:
        """Serialize DECODING request ``rid`` into one migration blob
        (:mod:`tpushare.serving.migrate`): the distinct physical pages
        its table references (content only for pages holding any
        COMMITTED position — pages reserved ahead of the write
        frontier carry garbage every consumer overwrites at
        ``length==p`` before it becomes attendable, so their bytes
        never travel), the table STRUCTURE (range -> local page
        index, which reproduces full-causal, ring, and prefix-mapped
        layouts alike), and the complete slot state including the
        current PRNG key data.  Read-only: the slot keeps serving
        until :meth:`pop_session`.  Raises ``KeyError`` for unknown
        rids and ``ValueError`` for mid-prefill requests (their pages
        are part-garbage; migration waits for activation)."""
        from . import migrate
        slot = self._slot_of(rid)
        s = self.slots[slot]
        row = self.page_table[slot]
        n_ranges = int(np.count_nonzero(row))
        page = self.page_size
        ids: List[int] = []
        local: Dict[int, int] = {}
        ranges: List[int] = []
        content = set()
        for j in range(n_ranges):
            p = int(row[j])
            if p not in local:
                local[p] = len(ids)
                ids.append(p)
            ranges.append(local[p])
            if j * page < s.length:
                content.add(local[p])
        content_idx = sorted(content)
        sel = np.asarray([ids[i] for i in content_idx], np.int32)
        arrays = {}
        for prefix, store in (("k", self.pools[0]), ("v", self.pools[1])):
            for name, leaf in _store_arrays(prefix, store):
                arrays[name] = np.asarray(leaf[:, sel])
        key_data = None
        if s.key is not None:
            key_data = np.asarray(
                jax.random.key_data(s.key)).tolist()
        meta = {
            "fingerprint": migrate.config_fingerprint(self.cfg,
                                                      self.page_size),
            # the originating request's fleet trace id (opaque; see
            # migrate.session_trace) — the receiver's decode spans
            # join the trace the prefill/drain sender started
            "trace": self._rid_traces.get(rid),
            "n_pages": len(ids),
            "content_pages": content_idx,
            "ranges": ranges,
            "slot": {
                "length": int(s.length),
                "remaining": int(s.remaining),
                "last_token": int(s.last_token),
                "output": [int(t) for t in s.output],
                "prompt_len": int(s.prompt_len),
                "temperature": float(s.temperature),
                "eos_id": (int(s.eos_id) if s.eos_id is not None
                           else None),
                "top_k": int(s.top_k),
                "top_p": float(s.top_p),
                "key_data": key_data,
                # adapter travels by NAME (pool rows are receiver-
                # local); the importer re-acquires it into its own
                # pool — a missing/None name is a base-model session
                "adapter": self._adapter_name_of(slot),
            },
        }
        blob = migrate.pack_session(meta, arrays)
        metrics.MIGRATION_BYTES.inc(len(blob), direction="out")
        return blob

    def _slot_of(self, rid: int) -> int:
        for i, s in self.slots.items():
            if s.request_id == rid:
                return i
        for i, p in self.prefilling.items():
            if p.request_id == rid:
                raise ValueError(f"request {rid} is mid-prefill; "
                                 f"sessions migrate at/after activation")
        raise KeyError(f"no decoding request {rid}")

    def pop_session(self, rid: int) -> None:
        """Release request ``rid``'s slot and pages WITHOUT completing
        or cancelling it — the sender-side end of a migration (the
        stream now lives in the exported blob).  The caller owns
        delivering the eventual result to the request's client."""
        slot = self._slot_of(rid)
        self._req_acct.pop(rid, None)
        self._rid_traces.pop(rid, None)
        self._release(slot)
        del self.slots[slot]

    def import_session(self, blob: bytes,
                       rid: Optional[int] = None) -> Optional[int]:
        """Scatter a migration blob into free pages and resume the
        session as a DECODING slot; returns its request id, or None on
        capacity backpressure (no free slot / pool cannot fit — the
        ``pool_full`` refusal the router's local-decode fallback keys
        on).  Raises :class:`~tpushare.serving.migrate.BlobError` /
        :class:`~tpushare.serving.migrate.ConfigMismatch` for blobs
        that can NEVER import here.  ``rid`` pins the restored
        request id (the spill tier re-imports under the original id so
        its sink wiring survives); default allocates a fresh one."""
        from . import migrate
        meta, arrays = migrate.unpack_session(blob)
        fp = migrate.config_fingerprint(self.cfg, self.page_size)
        if meta.get("fingerprint") != fp:
            raise migrate.ConfigMismatch(
                f"blob fingerprint {meta.get('fingerprint')} != "
                f"receiver {fp}")
        # structural validation BEFORE any state mutates: a malformed-
        # but-parsable header (corrupt peer, crafted request) must be
        # the counted bad_blob refusal, never an escaping IndexError
        # that could kill the serving loop mid-import
        try:
            need = int(meta["n_pages"])
            ranges = [int(li) for li in meta["ranges"]]
            content_idx = [int(i) for i in meta["content_pages"]]
            st = dict(meta["slot"])
            st_ints = {k: int(st[k]) for k in
                       ("length", "remaining", "last_token",
                        "prompt_len", "top_k")}
            st_out = [int(t) for t in st["output"]]
            st_temp = float(st["temperature"])
            st_top_p = float(st["top_p"])
            st_eos = (int(st["eos_id"]) if st.get("eos_id") is not None
                      else None)
            key = None
            if st.get("key_data") is not None:
                key = jax.random.wrap_key_data(jnp.asarray(
                    np.asarray(st["key_data"], np.uint32)))
            if not (1 <= need <= len(ranges) <= self.pages_per_slot):
                raise ValueError(f"{need} pages over {len(ranges)} "
                                 f"ranges does not fit the table")
            if any(li < 0 or li >= need for li in ranges) or \
                    any(i < 0 or i >= need for i in content_idx):
                raise ValueError("range/content index out of bounds")
        except (KeyError, TypeError, ValueError) as e:
            raise migrate.BlobError(
                f"malformed session meta: {e}") from None
        # STRIPE placement (round 17): the blob is layout-agnostic
        # (logical ranges + page content), so sessions migrate freely
        # between pools of DIFFERENT striping degrees — the receiver
        # re-allocates each blob page on the stripe its range demands.
        # A page referenced at ranges on different stripes (only a
        # ring layout produces multi-range pages, and ring configs
        # never fingerprint-match a striped receiver) cannot be
        # represented here and refuses as a malformed blob.
        stripe_of_local: Dict[int, int] = {}
        for j, li in enumerate(ranges):
            s = j % self.sp_shards
            if stripe_of_local.setdefault(li, s) != s:
                raise migrate.BlobError(
                    "session blob maps one page at ranges on "
                    "different position stripes; it cannot import "
                    "into this striped pool")
        need_by_stripe = [0] * self.sp_shards
        for li in range(need):
            need_by_stripe[stripe_of_local.get(li, 0)] += 1
        free = self.free_slots()
        if not free:
            return None
        # the session's adapter re-acquires into THIS pool by name; a
        # blob naming an adapter this receiver cannot serve is a
        # config mismatch (it could never decode correctly), while a
        # full pool is plain capacity backpressure like pages/slots
        ad_name = st.get("adapter")
        if ad_name is not None and not isinstance(ad_name, str):
            raise migrate.BlobError("session adapter must be a string")
        aidx = 0
        if ad_name:
            if self.adapter_pool is None:
                raise migrate.ConfigMismatch(
                    f"session rides adapter {ad_name!r} but the "
                    f"receiver has no adapter pool")
            aidx = self.adapter_pool.acquire(ad_name)
            if aidx is None:
                return None           # adapter-pool pressure
        if self._stripes_short(need_by_stripe):
            self._evict_prefixes(need_by_stripe)
        if self._stripes_short(need_by_stripe):
            if aidx and self.adapter_pool is not None:
                self.adapter_pool.release(aidx)
            return None
        slot = free[0]
        if aidx:
            self._slot_adapter[slot] = aidx
        pages = [self._free_by_stripe[stripe_of_local.get(li, 0)].pop()
                 for li in range(need)]
        if content_idx:
            sel = jnp.asarray([pages[i] for i in content_idx], jnp.int32)

            def rebuild(prefix, store):
                if isinstance(store, dict):
                    return {"q": jnp.asarray(arrays[f"{prefix}.q"]),
                            "s": jnp.asarray(arrays[f"{prefix}.s"])}
                return jnp.asarray(arrays[prefix])

            try:
                blocks = (rebuild("k", self.pools[0]),
                          rebuild("v", self.pools[1]))
                self.pools = _scatter_pages(self.pools, sel, blocks)
            except (KeyError, TypeError, ValueError) as e:
                self._free_pages_return(pages)
                self._release_adapter(slot)     # pin rolled back
                raise migrate.BlobError(
                    f"blob arrays do not match the pool layout: {e}") \
                    from None
        self.page_table[slot, :] = 0
        for j, li in enumerate(ranges):
            self.page_table[slot, j] = pages[li]
        self._slot_pages[slot] = pages
        self._update_page_gauges()
        if rid is None:
            rid = self._next_id
            self._next_id += 1
        else:
            self._next_id = max(self._next_id, rid + 1)
        self.slots[slot] = _Slot(
            request_id=rid, length=st_ints["length"],
            remaining=st_ints["remaining"],
            last_token=st_ints["last_token"],
            output=st_out,
            prompt_len=st_ints["prompt_len"],
            temperature=st_temp, key=key,
            eos_id=st_eos, top_k=st_ints["top_k"],
            top_p=st_top_p)
        self._acct_open(rid, st_ints["prompt_len"])
        trace = migrate.session_trace(meta)
        if trace:
            # the imported session's dispatches join the originating
            # request's fleet trace (guards/spans pick it up via
            # _rid_traces like any locally-admitted request)
            self._rid_traces[rid] = trace
        metrics.MIGRATION_BYTES.inc(len(blob), direction="in")
        return rid
