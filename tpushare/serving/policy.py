"""Tenant-isolation enforcement: pacing, admission verdicts, slack
reallocation (the ROADMAP-2 loop closed).

Round 4 proved the HBM fraction caps are ADVISORY on this backend
(COTENANCY_r04: every 0.22-grant tenant reached the full-chip ceiling)
and round 11 built the measurement substrate — per-tenant device-time
share vs HBM-fraction entitlement, Jain fairness, overshoot counters —
but the daemon only *observed* it.  This module is the enforcement
half, gpu_ext-style: a small pluggable policy layer hooked into choke
points that already exist, never a new dispatch path.

Three pieces:

* :func:`compute_verdicts` — the daemon-side policy math (pure, unit-
  tested directly): folds the ``aggregate_tenants`` view into one
  verdict per tenant, ``ok | pace:<rate> | refuse``, with SGDRC-style
  slack reallocation — a tenant under-using its entitlement donates
  the headroom to the over-users (proportionally to their
  entitlements), and the donation re-tightens the moment the donor's
  own usage returns.  The pace rate is *self-tightening*
  (``effective_entitlement / overshoot_ratio`` device-seconds per
  wall-second): the further over, the slower, so the cumulative share
  converges back under the pace threshold instead of plateauing at it.
* :class:`DispatchPacer` — the workload-side token bucket the
  ``MONITOR.dispatch_guard`` choke point consults: ``acquire(phase)``
  sleeps the SERVING LOOP before its next dispatch (never a hung
  worker, never inside a jitted program — the sleep happens before the
  guard's timer starts, so paced wall time is never attributed as
  device time), ``debit(phase, device_s)`` charges each dispatch's
  measured device residency against the bucket.
* :class:`PolicyClient` — applies the daemon's ``/usage`` response
  verdict (``contract.report_usage`` returns it) to the local pacer
  and keeps the admission-refusal window: a ``refuse`` verdict makes
  the LLM server answer 429 with a bounded-backoff ``Retry-After``
  (graceful: pacing before refusal, refusal counted and served —
  never a crash), cleared by the next ``ok``/``pace`` verdict.

Stdlib-only and pre-jax importable, like router.py and
telemetry/health.py (lint rule ``router-no-jax`` patrols both): the
policy layer adds ZERO device dispatches — it only spaces and gates
the ones the serving plane already makes (dispatch_audit Layer 4
checks any in-plane ``*.acquire`` pacing call rides a dispatch
guard).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from . import metrics

#: the daemon's enforcement modes (``--tenant-policy``): ``off`` issues
#: only ``ok`` verdicts (byte-identical serving), ``observe`` computes
#: and counts verdicts without any tenant acting on them (``mode`` in
#: the /usage response gates the client), ``enforce`` closes the loop
POLICY_MODES = ("off", "observe", "enforce")

#: reasons ``tpushare_tenant_admission_refused_total`` may carry
#: (enum-pinned in tests/test_metric_lint.py)
POLICY_REFUSAL_REASONS = ("over_share",)

#: a tenant is FLAGGED over-share (tpushare_tenant_share_overshoot_total,
#: the inspect OVER column) past this ratio of its raw entitlement —
#: the round-11 observation threshold, now defined here so the
#: enforcement thresholds below sit against it in one place
#: (plugin/status.py re-exports it for the existing consumers)
SHARE_OVERSHOOT_SLACK = 1.1

#: enforcement ladder thresholds against the EFFECTIVE (slack-
#: reallocated) entitlement: pacing engages below the observation
#: slack on purpose — the controller oscillates around PACE_RATIO, so
#: it must sit under the 1.1 bound the acceptance criteria (and the
#: overshoot counter) are stated against
PACE_RATIO = 1.05
#: past this ratio pacing has demonstrably not contained the tenant
#: (or it burst faster than the report loop): refuse admissions until
#: the share decays back into the pace band
REFUSE_RATIO = 1.3

#: refusal Retry-After bounds (seconds): exponential backoff per
#: consecutive refuse verdict, capped — bounded-backoff by contract
REFUSE_RETRY_AFTER_S = 1.0
REFUSE_RETRY_AFTER_MAX_S = 8.0

#: one pacing sleep never exceeds this (the loop stays responsive to
#: rate updates and cancellations; a large deficit paces over several
#: rounds instead of wedging one)
MAX_PACE_SLEEP_S = 2.0

#: small credit burst (seconds of device time at the paced rate) so
#: pacing spaces dispatches instead of oscillating around every one
PACE_BURST_S = 0.25

_PACE_PREFIX = "pace:"


def tenant_is_busy(t: dict) -> bool:
    """The DEMAND signal slack reallocation keys on: a tenant with
    queued admissions or active batcher slots has unmet/ongoing work —
    its under-use is starvation (or pacing), not idleness.  Reports
    without the serving signals (pure-training tenants, older
    workloads) read as idle: they volunteer their headroom exactly the
    way the pre-policy advisory world already let everyone take it."""
    return bool(t.get("queued") or t.get("occupancy"))


def effective_entitlements(tenants: Dict[str, dict]) -> Dict[str, float]:
    """SGDRC-style slack reallocation over the ``aggregate_tenants``
    per-tenant view: IDLE tenants using less than their entitlement
    donate the headroom (``entitlement - share``), and the pool is
    granted to the over-users proportionally to their entitlements.
    A donor's effective entitlement stays its own (its unused share is
    what it donates, not its claim); when the donor's demand returns
    (:func:`tenant_is_busy` — queued work or active slots), its
    donation vanishes on the next verdict and the over-users
    re-tighten.  The busy gate is what separates a genuinely idle
    co-tenant (whose headroom SHOULD flow — that is the whole point of
    sharing the chip) from a starved victim, whose involuntary
    under-use must never fund its antagonist.  No state; the
    reallocation is recomputed per report."""
    shares = {pod: t for pod, t in tenants.items()
              if t.get("share") is not None and t.get("entitlement")}
    donated = sum(t["entitlement"] - t["share"] for t in shares.values()
                  if t["share"] < t["entitlement"]
                  and not tenant_is_busy(t))
    over_ent = sum(t["entitlement"] for t in shares.values()
                   if t["share"] > t["entitlement"])
    out = {}
    for pod, t in shares.items():
        eff = t["entitlement"]
        if donated > 0 and over_ent > 0 and t["share"] > t["entitlement"]:
            eff += donated * (t["entitlement"] / over_ent)
        out[pod] = eff
    return out


def compute_verdicts(tenants: Dict[str, dict], mode: str) -> Dict[str, dict]:
    """Fold the per-tenant accounting view into policy verdicts.

    ``tenants`` is ``aggregate_tenants(...)["tenants"]``.  Returns
    ``{pod: {"verdict", "ratio", "effective_entitlement", "reason"}}``
    where verdict is ``"ok"``, ``"pace:<rate>"`` (rate in device-
    seconds per wall-second) or ``"refuse"``.  ``mode="off"`` issues
    only ``ok`` (effective entitlements still computed — the gauges
    render in observe-nothing deployments too).  Pure function."""
    if mode not in POLICY_MODES:
        raise ValueError(f"unknown policy mode {mode!r} "
                         f"(have {POLICY_MODES})")
    eff = effective_entitlements(tenants)
    out: Dict[str, dict] = {}
    for pod, t in tenants.items():
        e = eff.get(pod)
        share = t.get("share")
        ratio = (share / e) if (e and share is not None) else None
        verdict, reason = "ok", None
        if mode != "off" and ratio is not None:
            if ratio > REFUSE_RATIO:
                verdict, reason = "refuse", "over_share"
            elif ratio > PACE_RATIO:
                # self-tightening: rate shrinks with the overshoot, so
                # the cumulative share decays TOWARD the band instead
                # of riding its edge
                verdict = f"{_PACE_PREFIX}{e / ratio:.6f}"
        out[pod] = {"verdict": verdict, "ratio": ratio,
                    "effective_entitlement": e, "reason": reason}
    return out


def parse_pace_rate(verdict: str) -> Optional[float]:
    """The device-seconds-per-wall-second rate of a ``pace:`` verdict,
    None for anything else (including malformed rates — an unparsable
    verdict must degrade to un-paced, never crash the tenant)."""
    if not isinstance(verdict, str) or \
            not verdict.startswith(_PACE_PREFIX):
        return None
    try:
        rate = float(verdict[len(_PACE_PREFIX):])
    except ValueError:
        return None
    return rate if rate > 0 else None


#: Lock-discipline manifest — verified by tpushare.analysis.confinement
#: (Layer 3 of ``make lint``, same contract as telemetry/health.py):
#: every mutation of these attributes outside ``__init__`` sits inside
#: ``with self._lock:``.  The pacer is shared between the serving loop
#: (acquire on guard enter), the guard exit (debit), and the usage-
#: report thread (set_rate from verdicts).
_LOCK_GUARDED = {
    "DispatchPacer": ("_rate", "_deficit", "_t_mark"),
    "PolicyClient": ("_refuse_until", "_backoff_s", "_last_verdict"),
}


class DispatchPacer:
    """Token bucket over DEVICE time: the bucket drains by each
    dispatch's measured device residency (:meth:`debit` — the guard's
    own attribution, wall minus the tunnel-RPC constant) and refills at
    ``rate`` device-seconds per wall second.  :meth:`acquire` sleeps
    the caller — the serving loop, before its next dispatch — until
    the deficit clears (bounded per call; a large deficit paces over
    several rounds).  ``rate=None`` disarms: acquire is one lock-free
    attribute read, so an installed-but-idle pacer costs nothing on
    the guard hot path."""

    def __init__(self, rate: Optional[float] = None):
        self._lock = threading.Lock()
        self._rate: Optional[float] = rate if rate and rate > 0 else None
        self._deficit = 0.0          # device-seconds owed
        self._t_mark = time.monotonic()
        #: cumulative injected pacing sleep (monotonic counter, read by
        #: snapshot()/bench; the histogram carries the distribution)
        self.paced_s = 0.0
        self.paced_rounds = 0

    # -- configuration (usage-report thread) ---------------------------
    def set_rate(self, rate: Optional[float]) -> None:
        """Install/replace/clear the paced rate (device-seconds per
        wall-second).  Clearing forgives the deficit: an un-paced
        tenant must not carry debt into its next pacing episode."""
        with self._lock:
            self._settle_locked()
            self._rate = rate if rate and rate > 0 else None
            if self._rate is None:
                self._deficit = 0.0

    def rate(self) -> Optional[float]:
        return self._rate

    # -- the guard hook (serving loop thread) --------------------------
    def _settle_locked(self) -> None:
        now = time.monotonic()
        rate = self._rate
        if rate:
            self._deficit = max(-rate * PACE_BURST_S,
                                self._deficit - (now - self._t_mark) * rate)
        self._t_mark = now

    def acquire(self, phase: str) -> float:
        """Pre-dispatch pacing: sleep until the device-time deficit
        clears (bounded by :data:`MAX_PACE_SLEEP_S`).  Runs on the
        serving loop thread BEFORE the dispatch guard's timer starts —
        paced wall time is never attributed as device time, and the
        stall watchdog never sees it.  Returns the seconds slept."""
        if self._rate is None:          # lock-free disarmed fast path
            return 0.0
        with self._lock:
            self._settle_locked()
            rate = self._rate
            if rate is None or self._deficit <= 0:
                return 0.0
            # the sleep itself repays the deficit: the NEXT settle
            # credits the slept wall time at the paced rate, so the
            # deficit is deliberately not touched here
            wait = min(self._deficit / rate, MAX_PACE_SLEEP_S)
            self.paced_s += wait
            self.paced_rounds += 1
        time.sleep(wait)                # sleep OUTSIDE the lock
        metrics.POLICY_PACE_WAIT.observe(wait)
        return wait

    def debit(self, phase: str, device_s: float) -> None:
        """Post-dispatch charge: the guard's measured device residency
        drains the bucket (phase kept for symmetry/telemetry; the
        budget is chip-wide, exactly like the entitlement)."""
        if self._rate is None or not device_s or device_s <= 0:
            return
        with self._lock:
            self._settle_locked()
            if self._rate is not None:
                self._deficit += device_s

    def snapshot(self) -> dict:
        with self._lock:
            return {"rate": self._rate,
                    "deficit_s": round(self._deficit, 6),
                    "paced_s": round(self.paced_s, 6),
                    "paced_rounds": self.paced_rounds}


class PolicyClient:
    """The workload half of the verdict loop: feed each ``/usage``
    response (``contract.report_usage`` returns the parsed body)
    through :meth:`apply` and the local enforcement state follows —
    the pacer's rate tracks ``pace:`` verdicts, and ``refuse``
    verdicts open a bounded-backoff admission-refusal window the LLM
    server serves as 429 + ``Retry-After`` (never a crash; the window
    closes on the next non-refuse verdict or by timeout, so a dead
    daemon can never refuse forever).

    ``static_rate`` (the ``--pace-rate`` knob) is the floor
    configuration an ``ok`` verdict restores — a standalone tenant
    can self-pace without any daemon.  ``verdict_interval_s`` is the
    usage-report cadence: a refusal window must stay open until the
    NEXT verdict can arrive (with margin), or a tenant refused on a
    30-second report loop would admit freely for 29 of every 30
    seconds — the window is closed early by any ok/pace verdict, and
    the Retry-After the clients see stays the bounded backoff."""

    def __init__(self, pacer: Optional[DispatchPacer] = None,
                 static_rate: Optional[float] = None,
                 verdict_interval_s: float = 30.0):
        self.pacer = pacer if pacer is not None else DispatchPacer(
            rate=static_rate)
        self._static_rate = static_rate
        self._verdict_interval_s = max(0.0, float(verdict_interval_s))
        self._lock = threading.Lock()
        self._refuse_until = 0.0
        self._backoff_s = 0.0
        self._last_verdict: Optional[str] = None

    def apply(self, response: dict) -> Optional[str]:
        """Apply one /usage response.  Only ``mode == "enforce"``
        responses act (observe mode measures, off mode is inert — the
        tenant serves byte-identically); returns the verdict applied,
        or None when the response carried none / enforcement is off."""
        if not isinstance(response, dict):
            return None
        verdict = response.get("policy")
        if response.get("mode") != "enforce" or \
                not isinstance(verdict, str):
            return None
        rate = parse_pace_rate(verdict)
        if verdict == "refuse":
            with self._lock:
                self._backoff_s = min(
                    REFUSE_RETRY_AFTER_MAX_S,
                    (self._backoff_s * 2) if self._backoff_s
                    else REFUSE_RETRY_AFTER_S)
                # the window outlives the advertised backoff: it must
                # reach the NEXT verdict (1.25x the report cadence for
                # skew) or enforcement is inert between reports; an
                # ok/pace verdict closes it immediately below, and the
                # cap bounds a dead daemon's ghost refusal
                self._refuse_until = time.monotonic() + max(
                    self._backoff_s, self._verdict_interval_s * 1.25)
                self._last_verdict = verdict
            # refusal still paces whatever is already in flight: keep
            # the last paced rate rather than opening the throttle
            return verdict
        if rate is not None:
            self.pacer.set_rate(rate)
        elif verdict == "ok":
            self.pacer.set_rate(self._static_rate)
        else:
            return None                 # unknown verdict: ignore
        with self._lock:
            self._refuse_until = 0.0
            self._backoff_s = 0.0
            self._last_verdict = verdict
        return verdict

    def refusal_retry_after(self) -> float:
        """Seconds the admission gate should advertise in Retry-After:
        0 exactly when the refusal window is closed, else the BOUNDED
        backoff (never the whole window — the window spans report
        intervals so enforcement holds between verdicts, but a client
        retrying at the backoff cadence just meets the next 429, which
        is the graceful contract)."""
        with self._lock:
            remaining = self._refuse_until - time.monotonic()
            if remaining <= 0:
                return 0.0
            return min(self._backoff_s, remaining) or remaining

    def snapshot(self) -> dict:
        with self._lock:
            return {"last_verdict": self._last_verdict,
                    "refusing_for_s": round(
                        max(0.0, self._refuse_until - time.monotonic()),
                        3),
                    "backoff_s": self._backoff_s,
                    "pacer": self.pacer.snapshot()}
