"""``tpushare-router`` — the fleet front door over N LLM-server replicas.

Everything through round 14 makes ONE ``ContinuousService`` fast; this
process multiplies it: an HTTP router that spreads ``POST /generate``
traffic over N ``tpushare-llm-server`` replicas (co-tenants on shared
chips — COTENANCY_r04 measured quad tenants at 4.46x solo aggregate),
using only the surfaces the replicas already serve (``/metrics``,
``/healthz``, ``/drain``).  Three composable policies, applied in order:

1. **Health eviction** (always on): the scrape loop probes every
   replica's ``/healthz``; a non-200 answer (the WEDGED state — a
   stalled dispatch past deadline), a wedged body, or repeated
   transport failures DRAIN the replica from rotation (best-effort
   ``POST /drain`` so it finishes what it holds and admits nothing
   new).  A forward in flight to a replica that gets evicted is
   ABANDONED (the worker thread is left to finish on its own — never
   killed, the CLAUDE.md tunnel rule) and the request is re-dispatched
   to another replica with a bounded retry budget
   (``tpushare_router_retries_total``).  Re-dispatch is safe because
   ``/generate`` is by construction idempotent — same prompt, seed,
   and sampling knobs produce the same stream on every replica (shared
   init seed), and the abandoned forward's late response is discarded,
   so a client sees exactly one answer (DESIGN.md "Fleet routing").
2. **Prefix-cache affinity** (``--no-affinity`` disables): the longest
   committed prompt-prefix hash, at ``--prefix-block`` token
   granularity, maps to the replica that last served that prefix — the
   replica whose ``--prefix-cache`` pages already hold those tokens'
   KV.  The affinity target is used only while live and unsaturated
   (batch occupancy below ``--saturation``); otherwise the request
   falls back to the load policy (fresh pages beat a queued hit).
3. **Load-aware least-pending** (the fallback and the default): each
   replica's scraped serving metrics distill (via the same
   ``summarize_serving`` the inspect CLI uses) into a score of
   router-side in-flight forwards + batch occupancy + prefill queue
   depth + TTFT p99, with a FlexNPU-style prefill/decode split: a
   prefill-heavy request (long prompt relative to its ``max_new``)
   weights occupancy hardest — its prompt chunks would steal mixed-
   round budget from replicas deep in decode — while a decode-heavy
   request weights the prefill queue hardest (its tokens would wait
   behind queued prompts).  Scrapes lag by ``--scrape-interval``; the
   in-flight term is the router's own and keeps bursts from piling
   onto the replica whose scrape happens to look idle.

A fourth, tenant-aware rung (round 19, ``--status-endpoints``): the
scrape loop reads the daemon's per-tenant device-time-share series,
and a request whose body names an over-share tenant is STEERED —
affinity bypassed, pure load pick — so the noisy tenant's overflow
spreads to under-loaded replicas before its own process paces or
refuses it (the router is the gentlest rung of the enforcement
ladder; see DESIGN.md "Enforced sharing").

Stdlib-only, importable BEFORE jax, like ``telemetry/health.py`` — the
router allocates no backend and must never dial the TPU tunnel
(enforced: tpulint rule ``router-no-jax``).  Routing telemetry rides
the process-global registry and renders on this process's ``/metrics``
(``tpushare_router_*``; ``kubectl inspect tpushare --fleet`` scrapes
it).
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple, Union

from .. import telemetry
# trace-context wire format (stdlib, same no-jax contract): the router
# MINTS the fleet trace id for untraced requests and stamps a child
# context on every forward/retry/handoff so replica spans join it
from ..telemetry import propagation
from ..telemetry.trace import debug_trace_route
# the ONE exposition distiller (inspect --metrics uses the same): the
# router keys its load score on the identical fields the operator sees
from ..inspect.metricsview import summarize_serving
from ..utils.httpserver import JsonHTTPServer, RawBody
from . import metrics
# the ONE over-share threshold (stdlib policy module, same no-jax
# contract as this file): the steering verdict must agree with the
# daemon's OVER flag and the pacing thresholds
from .policy import SHARE_OVERSHOOT_SLACK

log = logging.getLogger("tpushare.router")

#: the policy label values tpushare_router_requests_total may carry
#: (enum-linted in tests/test_metric_lint.py, like the fallback reasons)
ROUTER_POLICIES = ("affinity", "load", "retry")

#: outcomes of a disaggregated prefill->decode hand-off — the
#: enumerated values of ``tpushare_router_handoffs_total{outcome=}``
#: (enum-linted): ``ok`` = the blob imported on a decode replica;
#: ``local_fallback`` = every decode target refused/failed, so the
#: blob went back to the PREFILL replica for local decode (the
#: counted receiver-pool-full degradation); ``reprefill`` = the blob
#: could not land anywhere (e.g. the receiver wedged mid-transfer),
#: so the request re-dispatched as a plain /generate from scratch —
#: never corrupted, never duplicated, just re-prefilled
HANDOFF_OUTCOMES = ("ok", "local_fallback", "reprefill")

#: longest prompt prefix the affinity hash considers, in blocks — a cap
#: so hashing cost stays O(blocks * prefix), not O(len^2) on huge prompts
MAX_AFFINITY_BLOCKS = 32


class Replica:
    """Router-side view of one LLM-server replica.

    Mutable fields are guarded by the router's lock except ``inflight``
    decrements, which the forward worker performs in its ``finally`` —
    also under the router's lock (the worker may outlive an eviction;
    its late decrement must not corrupt the count)."""

    def __init__(self, name: str, address: str, role: str = "any"):
        self.name = name
        self.address = address            # "host:port"
        #: disaggregation role: "prefill" replicas take new prompts,
        #: "decode" replicas take the handed-off KV and decode to
        #: completion, "any" serves both (the non-disaggregated fleet)
        self.role = role
        self.summary: Optional[dict] = None   # last summarize_serving
        self.evicted_reason: Optional[str] = None
        self.inflight = 0                 # router-side pending forwards
        self.consecutive_failures = 0
        self.requests = 0                 # successful forwards
        self.affinity_hits = 0
        #: the ROUTER drained this replica (eviction): recovery must
        #: undrain it, or it would 503 forever; an operator's own drain
        #: (this flag unset) is never undone by the router
        self.drain_sent = False
        #: consecutive scrape passes observed healthy AND not draining
        #: while drain_sent is set — after a grace pass the stale claim
        #: clears (the replica restarted, or our drain never landed),
        #: so a FUTURE operator drain cannot be mistaken for ours
        self.clean_passes = 0

    @property
    def in_rotation(self) -> bool:
        return self.evicted_reason is None

    def view(self) -> dict:
        """The /fleet JSON entry (point-in-time; lock held by caller)."""
        return {"name": self.name, "address": self.address,
                "role": self.role,
                "up": self.in_rotation,
                "evicted_reason": self.evicted_reason,
                "inflight": self.inflight,
                "requests": self.requests,
                "affinity_hits": self.affinity_hits,
                "summary": self.summary}


class FleetRouter:
    """HTTP front door spreading /generate over N replicas.

    ``replicas``: "host:port" strings (names default ``r0..rN``) or
    ``(name, "host:port")`` pairs.  ``port=0`` binds an ephemeral port
    (tests); the CLI default is 8800.
    """

    def __init__(self, replicas: Sequence[Union[str, Tuple[str, str]]],
                 port: int = 0, addr: str = "127.0.0.1", *,
                 prefill_replicas: Sequence[Union[str,
                                                  Tuple[str, str]]] = (),
                 decode_replicas: Sequence[Union[str,
                                                 Tuple[str, str]]] = (),
                 affinity: bool = True,
                 prefix_block: int = 16,
                 max_affinity_entries: int = 4096,
                 scrape_interval_s: float = 2.0,
                 scrape_timeout_s: float = 2.0,
                 max_retries: int = 2,
                 saturation: float = 0.95,
                 request_timeout_s: float = 600.0,
                 eviction_failures: int = 2,
                 prefill_heavy_ratio: float = 2.0,
                 watch_poll_s: float = 0.05,
                 status_endpoints: Sequence[str] = ()):
        self._replicas: List[Replica] = []
        # TENANT-AWARE STEERING (round 19): the scrape loop also reads
        # each listed daemon exposition's per-tenant share-vs-
        # entitlement series; a request whose body names an over-share
        # tenant ("tenant": <pod>) skips prefix affinity and routes by
        # pure load — its overflow spreads to the under-loaded replica
        # BEFORE the tenant is paced locally (the router's rung of the
        # enforcement ladder: steer, then pace, then refuse).
        self._status_endpoints = [e for e in status_endpoints if e]
        self._over_share: set = set()
        #: last successful per-endpoint verdict sets: an unreachable
        #: daemon KEEPS its tenants' last verdicts (a partial outage
        #: must not silently un-steer one daemon's noisy tenants while
        #: the others still answer)
        self._over_share_by_ep: Dict[str, set] = {}

        def _add(specs, role, prefix):
            for i, spec in enumerate(specs):
                if isinstance(spec, str):
                    self._replicas.append(
                        Replica(f"{prefix}{i}", spec, role=role))
                else:
                    name, address = spec
                    self._replicas.append(
                        Replica(name, address, role=role))

        _add(replicas, "any", "r")
        _add(prefill_replicas, "prefill", "p")
        _add(decode_replicas, "decode", "d")
        # PREFILL/DECODE DISAGGREGATION (FlexNPU taken to its
        # conclusion): with both role lists populated, every /generate
        # prefills on a prefill replica (phase="prefill" -> session
        # blob at the activation boundary) and the router streams the
        # blob to the least-loaded decode replica's /migrate_in — a
        # prefill storm saturates prefill replicas while decode
        # replicas keep serving pure-decode rounds.
        self._disagg = bool(prefill_replicas) and bool(decode_replicas)
        if (prefill_replicas or decode_replicas) and not self._disagg:
            raise ValueError("disaggregation needs BOTH prefill and "
                             "decode replicas")
        if not self._replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in self._replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self._affinity = bool(affinity)
        self._prefix_block = max(1, int(prefix_block))
        self._max_affinity_entries = int(max_affinity_entries)
        #: prefix-block hash -> replica NAME, LRU-bounded (an evicted
        #: entry just means one load-routed request re-warms the pages)
        self._affinity_map: "OrderedDict[int, str]" = OrderedDict()
        self._scrape_interval_s = float(scrape_interval_s)
        self._scrape_timeout_s = float(scrape_timeout_s)
        self._max_retries = max(0, int(max_retries))
        self._saturation = float(saturation)
        self._request_timeout_s = float(request_timeout_s)
        self._eviction_failures = max(1, int(eviction_failures))
        self._prefill_heavy_ratio = float(prefill_heavy_ratio)
        self._watch_poll_s = float(watch_poll_s)
        self._retries = 0
        self._lock = threading.Lock()
        self._halt = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        # one persistent pool for the life of the router: the scrape
        # loop fires every --scrape-interval forever, and rebuilding a
        # pool per pass would churn up to 16 OS threads each time
        self._scrape_pool = ThreadPoolExecutor(
            max_workers=min(16, len(self._replicas)),
            thread_name_prefix="tpushare-router-scrape")
        for r in self._replicas:
            metrics.ROUTER_REPLICA_UP.set(1.0, replica=r.name)
        self._http = JsonHTTPServer(port, addr, routes={
            ("POST", "/generate"): self._generate,
            ("GET", "/healthz"): self._healthz,
            ("GET", "/fleet"): self._fleet,
            ("GET", "/metrics"): lambda _: (
                200, RawBody(telemetry.REGISTRY.render(),
                             telemetry.PROM_CONTENT_TYPE)),
            # the router's own forward spans — one of the tracks
            # `inspect --trace` merges into the fleet timeline
            ("GET", "/debug/trace"): debug_trace_route,
        })
        self.port = self._http.port

    # -- lifecycle -----------------------------------------------------
    def _start_scrape(self) -> None:
        if self._scrape_thread is None:
            self._scrape_thread = threading.Thread(
                target=self._scrape_loop, daemon=True,
                name="tpushare-router-scrape")
            self._scrape_thread.start()

    def start(self) -> "FleetRouter":
        self._http.start()
        self._start_scrape()
        return self

    def serve_forever(self) -> None:
        self._start_scrape()
        self._http.serve_forever()

    def stop(self) -> None:
        self._halt.set()
        self._scrape_pool.shutdown(wait=False)
        self._http.stop()

    # -- scrape + health loop ------------------------------------------
    def _scrape_loop(self) -> None:
        self.scrape_once()       # initial verdict before first request
        while not self._halt.wait(self._scrape_interval_s):
            self.scrape_once()

    def scrape_once(self) -> None:
        """One health+metrics pass over the fleet.  Public so tests and
        the bench drive the verdict deterministically (the loop calls
        this too).  Replicas are probed CONCURRENTLY: one hung replica
        must not delay the eviction verdict on the rest."""
        try:
            list(self._scrape_pool.map(self._scrape_replica,
                                       self._replicas))
        except RuntimeError:
            pass                 # pool shut down mid-pass (stop())
        self._scrape_tenants()

    def _scrape_tenants(self) -> None:
        """Refresh the over-share tenant set from the configured daemon
        expositions (``--status-endpoints``): a tenant whose device-
        time share exceeds its EFFECTIVE (slack-reallocated)
        entitlement past the shared overshoot slack steers to pure
        load routing.  Best-effort — an unreachable daemon keeps the
        last verdict (steering is an optimization rung; pacing and
        refusal enforce regardless)."""
        if not self._status_endpoints:
            return
        for addr in self._status_endpoints:
            try:
                with urllib.request.urlopen(
                        f"http://{addr}/metrics",
                        timeout=self._scrape_timeout_s) as resp:
                    parsed = telemetry.parse_text(resp.read().decode())
            except Exception as e:
                # keep this endpoint's LAST verdicts: a partial daemon
                # outage must not un-steer its tenants while the other
                # daemons still answer
                log.debug("tenant scrape failed for %s: %s", addr, e)
                continue

            def series(name):
                return {labels.get("tenant"): value
                        for labels, value in
                        parsed["samples"].get(name, ())}

            share = series("tpushare_tenant_device_share")
            eff = series("tpushare_tenant_effective_entitlement_share")
            ent = series("tpushare_tenant_entitlement_share")
            over = set()
            for tenant, s in share.items():
                base = eff.get(tenant, ent.get(tenant))
                if tenant and base and s > base * SHARE_OVERSHOOT_SLACK:
                    over.add(tenant)
            with self._lock:
                self._over_share_by_ep[addr] = over
                self._over_share = set().union(
                    *self._over_share_by_ep.values())

    def _scrape_replica(self, r: Replica) -> None:
        ok, reason = self._probe_health(r)
        if ok:
            try:
                text = self._get(r, "/metrics")
                summary = summarize_serving(telemetry.parse_text(text))
                with self._lock:
                    r.summary = summary
            except Exception as e:
                # metrics failing while /healthz answers is odd but not
                # an eviction by itself: route on the stale summary
                log.debug("metrics scrape failed for %s: %s", r.name, e)
            with self._lock:
                # drain-claim hygiene: healthy AND not draining means
                # our drain is no longer in effect (the replica
                # restarted, or the POST never landed) — after TWO
                # such passes (one pass of grace covers a drain POST
                # still in flight) the stale claim clears, so a later
                # OPERATOR drain cannot be mistaken for ours
                if r.drain_sent:
                    r.clean_passes += 1
                    if r.clean_passes >= 2:
                        r.drain_sent = False
                        r.clean_passes = 0
            self._restore(r)
        elif reason == "draining" and r.drain_sent:
            # the replica recovered from whatever evicted it and is now
            # refusing admissions only because WE drained it — undo
            # that (the next scrape pass restores rotation); a drain
            # the router did not send is an operator's and stays.
            # drain_sent clears only on a CONFIRMED undrain (inside
            # _send_drain), so a lost undrain POST retries next pass.
            log.info("replica %s healthy but still carrying our drain; "
                     "undraining", r.name)
            with self._lock:
                r.clean_passes = 0
            threading.Thread(target=self._send_drain,
                             args=(r,), kwargs={"undrain": True},
                             daemon=True,
                             name=f"tpushare-router-undrain-{r.name}"
                             ).start()
        else:
            with self._lock:
                r.clean_passes = 0
            self._evict(r, reason)

    def _get(self, r: Replica, path: str) -> str:
        with urllib.request.urlopen(f"http://{r.address}{path}",
                                    timeout=self._scrape_timeout_s) as resp:
            return resp.read().decode()

    def _probe_health(self, r: Replica) -> Tuple[bool, str]:
        """(in_rotation verdict, reason).  Non-200 is the WEDGED
        contract (health plane: /healthz is non-200 exactly when
        WEDGED); a 200 body may still carry a state dict (DEGRADED and
        CPU_FALLBACK keep serving — they stay in rotation).  A DRAINING
        replica refuses admissions, so it is out of rotation too —
        whether the drain was ours (recovery undrains it, see
        :meth:`_scrape_replica`) or an operator's rolling restart
        (which the router must never undo)."""
        try:
            body = self._get(r, "/healthz")
        except urllib.error.HTTPError as e:
            # the non-200 body still matters: a WEDGED replica that is
            # ALSO operator-draining must evict with the draining
            # reason, or the eviction would post an ownership-claiming
            # drain whose later undrain cancels the operator's
            try:
                if json.loads(e.read()).get("draining"):
                    return False, "draining"
            except Exception:
                pass
            return False, f"healthz {e.code}"
        except Exception as e:
            return False, f"unreachable ({type(e).__name__})"
        try:
            parsed = json.loads(body)
            state = parsed.get("state")
            draining = bool(parsed.get("draining"))
        except (json.JSONDecodeError, AttributeError):
            state, draining = None, False     # plain "ok\n"
        if draining:                      # out of rotation whatever the
            return False, "draining"      # state says — and the reason
        if state == "wedged":             # must be draining for the
            return False, "wedged"        # ownership protocol
        return True, ""

    def _evict(self, r: Replica, reason: str) -> None:
        with self._lock:
            if not r.in_rotation:
                r.evicted_reason = reason     # keep the freshest verdict
                return
            r.evicted_reason = reason
        log.warning("evicting replica %s from rotation: %s", r.name,
                    reason)
        metrics.ROUTER_EVICTIONS.inc(replica=r.name)
        metrics.ROUTER_REPLICA_UP.set(0.0, replica=r.name)
        if reason == "draining":
            # already draining — and NOT by us: posting our own drain
            # here would claim ownership (drain_sent) and make recovery
            # undo what is really an operator's rolling restart
            return
        # Best-effort graceful drain in its own thread: a wedged
        # replica's HTTP surface may hang past any timeout we pick, and
        # the scrape pass must not wait on it.  _send_drain remembers
        # WE drained it, so recovery can undo exactly our drain and no
        # one else's.
        threading.Thread(target=self._send_drain, args=(r,), daemon=True,
                         name=f"tpushare-router-drain-{r.name}").start()

    def _send_drain(self, r: Replica, undrain: bool = False) -> None:
        """POST /drain (or the undrain) to ``r``, keeping the
        drain-ownership flag truthful: claimed BEFORE the drain POST
        (an ambiguous timeout may still land server-side, and an
        unowned landed drain would strand the replica 503ing forever),
        DISCLAIMED when the connection provably never happened (e.g.
        refused at startup while the replica is still compiling — a
        stale claim there would make the router undo the operator's
        next rolling-restart drain), and cleared only by a CONFIRMED
        undrain (a lost undrain retries next scrape pass)."""
        if not undrain:
            with self._lock:
                r.drain_sent = True
                r.clean_passes = 0
        try:
            req = urllib.request.Request(
                f"http://{r.address}/drain",
                data=json.dumps({"undrain": True}).encode()
                if undrain else b"{}",
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(
                    req, timeout=self._scrape_timeout_s):
                pass
            if undrain:
                with self._lock:
                    r.drain_sent = False   # confirmed: our drain is gone
        except Exception as e:
            reason = getattr(e, "reason", e)
            if not undrain and isinstance(reason, ConnectionError):
                with self._lock:
                    r.drain_sent = False   # never connected: no drain
                    # landed, so there is nothing of ours to undo
            log.debug("%s of %s failed (%s); eviction stands",
                      "undrain" if undrain else "drain", r.name, e)

    def _restore(self, r: Replica) -> None:
        # drain_sent deliberately NOT cleared here: our drain POST may
        # still be in flight while the replica probes healthy, and
        # dropping ownership now would make the late-landing drain read
        # as an operator's — permanently out of rotation.  The flag
        # clears only on a CONFIRMED undrain (_send_drain); the cost of
        # keeping it is one spurious undrain round-trip in the
        # drain-POST-was-lost corner, which self-corrects.
        with self._lock:
            if r.in_rotation:
                r.consecutive_failures = 0
                return
            r.evicted_reason = None
            r.consecutive_failures = 0
        log.info("replica %s recovered; back in rotation", r.name)
        metrics.ROUTER_REPLICA_UP.set(1.0, replica=r.name)

    def _note_failure(self, r: Replica, reason: str) -> None:
        """A forward to ``r`` failed.  Transport failures accumulate
        toward eviction (the scrape loop restores on recovery); the
        verdict is the router's own — it must not wait for the next
        scrape pass to stop picking a dead replica."""
        with self._lock:
            r.consecutive_failures += 1
            over = r.consecutive_failures >= self._eviction_failures
        if over:
            self._evict(r, f"{self._eviction_failures} consecutive "
                           f"forward failures ({reason})")

    # -- routing policies ----------------------------------------------
    def _prefix_hashes(self, tokens: List[int]) -> List[int]:
        """Prefix-block hashes, LONGEST first (the lookup wants the
        most-specific committed prefix; registration wants them all)."""
        n_blocks = min(len(tokens) // self._prefix_block,
                       MAX_AFFINITY_BLOCKS)
        return [hash(tuple(tokens[:i * self._prefix_block]))
                for i in range(n_blocks, 0, -1)]

    def _prefill_heavy(self, tokens: Optional[List[int]],
                       max_new: int) -> bool:
        """FlexNPU-style request class: a prompt long relative to its
        generation budget is prefill work; the rest is decode work."""
        if not tokens:
            return False
        return len(tokens) >= self._prefill_heavy_ratio * max(1, max_new)

    @staticmethod
    def _load_score(r: Replica, prefill_heavy: bool) -> float:
        """Least-pending score (LOWER routes first).  The in-flight
        term is router-side truth; the scraped terms are the replica's
        own serving plane, normalized to comparable magnitudes:
        occupancy is already a fraction, the prefill queue depth maps
        through q/(q+4) (4 queued prompts ≈ a half-full replica), and
        TTFT p99 clamps at one second."""
        s = r.summary or {}
        occ = s.get("occupancy") or 0.0
        pq = s.get("prefill_queue") or 0.0
        pq_n = pq / (pq + 4.0)
        ttft_n = min(1.0, s.get("ttft_p99_s") or 0.0)
        if prefill_heavy:
            shape = 2.0 * occ + 0.5 * pq_n
        else:
            shape = 2.0 * pq_n + 0.5 * occ
        return r.inflight + shape + 0.5 * ttft_n

    def _saturated(self, r: Replica) -> bool:
        occ = (r.summary or {}).get("occupancy")
        return occ is not None and occ >= self._saturation

    def _repoint_affinity(self, tokens: Optional[List[int]],
                          name: str,
                          adapter: Optional[str] = None) -> None:
        """Re-register a prompt's prefix-block hashes (and its adapter
        hash) to ``name`` — after a disaggregated hand-off the DECODE
        replica holds the session's pages AND its re-acquired adapter,
        so it is the new affinity target for both signals."""
        if not self._affinity or not (tokens or adapter):
            return
        hashes = self._prefix_hashes(tokens) if tokens else []
        if adapter:
            hashes = hashes + [self._adapter_hash(adapter)]
        with self._lock:
            for h in hashes:
                self._affinity_map[h] = name
                self._affinity_map.move_to_end(h)
            while len(self._affinity_map) > self._max_affinity_entries:
                self._affinity_map.popitem(last=False)

    @staticmethod
    def _adapter_hash(adapter: str) -> int:
        """Affinity-map key for an ADAPTER name — same LRU map as the
        prefix-block hashes, namespaced so a token-prefix hash can
        never collide with an adapter name's."""
        return hash(("adapter", adapter))

    def _pick(self, tokens: Optional[List[int]], prefill_heavy: bool,
              exclude: Sequence[str],
              role: Optional[str] = None,
              steer: bool = False,
              adapter: Optional[str] = None
              ) -> Tuple[Optional[Replica], str, bool]:
        """Choose a replica; returns (replica, policy, adapter_hit).
        Re-dispatch picks (``exclude`` non-empty) are pure load picks
        labeled ``retry`` — the affinity target just failed or is
        excluded, and a 'hit' that re-routes is not a hit.  ``role``
        restricts the candidates to that disaggregation role.
        ``steer`` (an over-share tenant's request) bypasses affinity
        ENTIRELY — lookup and registration: the overflow must spread
        by load, and registering its prefixes to the spread target
        would drag the tenant's future traffic after it.  ``adapter``
        (the request body's adapter name) is the STRONGEST affinity
        signal and is consulted BEFORE the prompt-prefix hashes: the
        replica that last served this adapter holds it resident in
        its pool — routing elsewhere costs a load (and maybe an
        eviction) there, which is what makes thousands of adapters
        per FLEET tractable (each stays hot on ~one replica).
        Saturated targets fall back to load like prefix hits.
        Increments the pick's in-flight count under the lock (the
        caller's forward owns the decrement)."""
        # hash once, OUTSIDE the lock (tuple-hashing long prompts is
        # the expensive part, and this lock is the front door's one
        # hot lock); the list serves both the lookup and registration
        hashes = (self._prefix_hashes(tokens)
                  if self._affinity and tokens and not steer else ())
        ahash = (self._adapter_hash(adapter)
                 if self._affinity and adapter and not steer else None)
        with self._lock:
            candidates = [r for r in self._replicas
                          if r.in_rotation and r.name not in exclude
                          and (role is None or r.role == role)]
            if not candidates:
                return None, "load", False
            chosen: Optional[Replica] = None
            adapter_hit = False
            policy = "retry" if exclude else "load"
            if not exclude:
                by_name = {r.name: r for r in candidates}
                if ahash is not None:
                    r = by_name.get(self._affinity_map.get(ahash, ""))
                    if r is not None and not self._saturated(r):
                        chosen, policy = r, "affinity"
                        adapter_hit = True
                if chosen is None:
                    for h in hashes:
                        r = by_name.get(self._affinity_map.get(h, ""))
                        if r is not None and not self._saturated(r):
                            chosen, policy = r, "affinity"
                            break
            if chosen is None:
                chosen = min(candidates,
                             key=lambda r: self._load_score(
                                 r, prefill_heavy))
            reg = list(hashes) + ([ahash] if ahash is not None else [])
            if reg:
                # register every block prefix (and the adapter) to the
                # chosen replica — its pages/pool will hold them once
                # admitted; LRU-bounded
                for h in reg:
                    self._affinity_map[h] = chosen.name
                    self._affinity_map.move_to_end(h)
                while len(self._affinity_map) > self._max_affinity_entries:
                    self._affinity_map.popitem(last=False)
            chosen.inflight += 1
            return chosen, policy, adapter_hit

    # -- forwarding ----------------------------------------------------
    @staticmethod
    def _relay_headers(headers) -> dict:
        """The replica response headers the router must relay: today
        just Retry-After (the tenant-policy 429's bounded backoff —
        stripping it would defeat the pacing the 429 exists for)."""
        v = headers.get("Retry-After") if headers is not None else None
        return {"Retry-After": v} if v else {}

    def _forward(self, r: Replica, data: bytes,
                 path: str = "/generate") -> Tuple[int, object, dict]:
        req = urllib.request.Request(
            f"http://{r.address}{path}", data=data,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(
                    req, timeout=self._request_timeout_s) as resp:
                return (resp.status, json.loads(resp.read()),
                        self._relay_headers(resp.headers))
        except urllib.error.HTTPError as e:
            hdrs = self._relay_headers(e.headers)
            try:
                return e.code, json.loads(e.read()), hdrs
            except Exception:
                return (e.code, {"Error": f"replica answered {e.code}"},
                        hdrs)

    def _forward_watched(self, r: Replica, data: bytes,
                         path: str = "/generate"
                         ) -> Optional[Tuple[int, object, dict]]:
        """Forward in a worker thread, watching the replica's rotation
        state: if ``r`` is evicted while the forward is in flight, the
        worker is ABANDONED (left to finish; never killed — its late
        response is discarded) and None is returned so the caller
        re-dispatches.  None also covers transport errors and the
        request deadline."""
        result: Dict[str, object] = {}
        done = threading.Event()

        def worker():
            try:
                result["resp"] = self._forward(r, data, path=path)
            except Exception as e:
                result["err"] = e
            finally:
                with self._lock:
                    r.inflight = max(0, r.inflight - 1)
                done.set()

        threading.Thread(target=worker, daemon=True,
                         name=f"tpushare-router-fwd-{r.name}").start()
        deadline = time.monotonic() + self._request_timeout_s
        while not done.wait(self._watch_poll_s):
            if not r.in_rotation or time.monotonic() > deadline:
                return None
        if "err" in result:
            return None
        return result["resp"]            # type: ignore[return-value]

    # -- routes --------------------------------------------------------
    @staticmethod
    def _request_tokens(body: dict) -> Optional[List[int]]:
        """The first prompt row when it is well-formed token ints (the
        affinity/classification input); None for text-mode or malformed
        bodies — those still forward, the REPLICA owns validation."""
        tokens = body.get("tokens")
        if (isinstance(tokens, list) and tokens
                and isinstance(tokens[0], list) and tokens[0]
                and all(isinstance(t, int) for t in tokens[0])):
            return tokens[0]
        return None

    def _generate(self, body):
        if not isinstance(body, dict):
            return 400, {"Error": "body must be a JSON object"}
        # fleet trace: continue the caller's context or mint one (the
        # router is the trace root for unadorned clients); t0 anchors
        # the critical-path hop decomposition — router_queue is receipt
        # to the first forward, and the disaggregated hops below
        # partition the REMAINING wall exactly (their sum is the
        # router's measured request wall)
        t0 = time.perf_counter()
        ctx = propagation.extract(body) or propagation.new_context()
        tokens = self._request_tokens(body)
        try:
            max_new = int(body.get("max_new_tokens", 32))
        except (TypeError, ValueError):
            max_new = 32                  # replica 400s the real parse
        prefill_heavy = self._prefill_heavy(tokens, max_new)
        # tenant-aware steering: an over-share tenant's overflow
        # spreads by pure load instead of piling onto its warm
        # affinity replica — the enforcement rung BEFORE local pacing
        tenant = body.get("tenant")
        steer = False
        if isinstance(tenant, str) and tenant:
            with self._lock:
                steer = tenant in self._over_share
            if steer:
                metrics.ROUTER_STEERED.inc()
        # adapter affinity (round 20): a request naming a LoRA adapter
        # routes to the replica already holding it resident
        adapter = body.get("adapter")
        if not (isinstance(adapter, str) and adapter):
            adapter = None
        if self._disagg:
            return self._generate_disagg(body, tokens, steer=steer,
                                         adapter=adapter, ctx=ctx, t0=t0)
        return self._forward_balanced(body, tokens, prefill_heavy,
                                      role=None, steer=steer,
                                      adapter=adapter, ctx=ctx, t0=t0)

    def _forward_balanced(self, body, tokens, prefill_heavy,
                          role: Optional[str] = None,
                          steer: bool = False,
                          adapter: Optional[str] = None,
                          ctx: "Optional[propagation.TraceContext]" = None,
                          t0: Optional[float] = None):
        """The plain health/affinity/load retry loop over one role
        class (None = the whole fleet) — the non-disaggregated
        /generate path, and the re-prefill fallback the disaggregated
        one degrades to.  ``ctx`` stamps a CHILD context per forward
        attempt (each retry is its own span on the replica); ``t0`` is
        set only by the top-level /generate entry and arms the
        router_queue hop observation (the re-prefill fallback already
        observed it)."""
        data = json.dumps(body).encode()
        tried: List[str] = []
        for attempt in range(self._max_retries + 1):
            replica, policy, ahit = self._pick(
                tokens, prefill_heavy, tried, role=role, steer=steer,
                adapter=adapter)
            if replica is None:
                if tried:
                    # candidates exist but were all tried and failed —
                    # that is the 502 story below, not a fleet outage
                    break
                return 503, {"Error": "no replica in rotation"}
            if attempt:
                with self._lock:
                    self._retries += 1
                metrics.ROUTER_RETRIES.inc()
            if ctx is not None:
                # fresh span id per ATTEMPT: a retried request shows
                # two replica-side spans under one trace, not one
                # ambiguous span claimed by both forwards
                data = json.dumps(
                    propagation.inject(body,
                                       propagation.child(ctx))).encode()
            if t0 is not None:
                metrics.REQUEST_HOP.observe(time.perf_counter() - t0,
                                            hop="router_queue")
                t0 = None
            with telemetry.span("router.forward", cat="router",
                                replica=replica.name,
                                trace=ctx.trace_id if ctx else None):
                out = self._forward_watched(replica, data)
            if out is not None and out[0] < 500:
                with self._lock:
                    replica.requests += 1
                    # "consecutive" means it: a success between two
                    # failures restarts the eviction countdown
                    replica.consecutive_failures = 0
                    if policy == "affinity" and not ahit:
                        replica.affinity_hits += 1
                metrics.ROUTER_REQUESTS.inc(replica=replica.name,
                                            policy=policy)
                # the two affinity signals count SEPARATELY: a pick
                # from the adapter hash is an adapter hit only (the
                # prefix series stays the prefix-cache hit rate)
                if policy == "affinity" and not ahit:
                    metrics.ROUTER_AFFINITY_HITS.inc(
                        replica=replica.name)
                if ahit:
                    metrics.ROUTER_ADAPTER_AFFINITY_HITS.inc(
                        replica=replica.name)
                return out          # (code, payload, relayed headers)
            if out is not None and out[0] == 503 and isinstance(
                    out[1], dict) and "draining" in str(
                        out[1].get("Error", "")):
                # the replica refuses because it is DRAINING (caught
                # here before the next scrape pass sees it): evict with
                # the draining reason so no ownership-claiming drain of
                # our own is posted — counting this as a transport
                # failure would later undo an OPERATOR's drain
                self._evict(replica, "draining")
            elif out is None:
                # abandoned (evicted mid-flight, transport error, or
                # deadline): the transport-level failure class that
                # accumulates toward eviction
                self._note_failure(
                    replica, "abandoned (evicted mid-flight, "
                             "transport error, or deadline)")
            # else: an HTTP 5xx APPLICATION response — the replica's
            # transport and HTTP stack are provably alive, so only
            # re-dispatch; counting it toward transport eviction would
            # let one poison request drain every healthy replica.
            # Replica-health verdicts for a 500-spewing process belong
            # to the /healthz scrape loop.
            tried.append(replica.name)
        return 502, {"Error": f"all forwards failed "
                              f"(tried {', '.join(tried)})"}

    # -- disaggregated prefill/decode routing ---------------------------
    def _generate_disagg(self, body, tokens, steer: bool = False,
                         adapter: Optional[str] = None,
                         ctx: "Optional[propagation.TraceContext]" = None,
                         t0: Optional[float] = None):
        """Prefill/decode-disaggregated /generate: the prompt prefills
        on a PREFILL replica (``phase="prefill"`` — the replica answers
        with the session blob at the activation boundary), then the
        blob streams to the least-loaded DECODE replica's /migrate_in,
        which serves the decode to completion.  Decode replicas never
        see prompt chunks, so a prefill storm cannot steal their
        mixed-round budget — the isolation the co-resident mixed step
        cannot provide.

        Degradation ladder (every rung counted in
        ``tpushare_router_handoffs_total{outcome=}``): decode target
        refuses (pool full) or fails mid-transfer -> the blob goes
        BACK to the prefill replica for local decode
        (``local_fallback``); that too fails -> plain re-prefill
        through the whole fleet (``reprefill`` — the request re-runs
        from scratch, so a WEDGED receiver can delay a stream but
        never corrupt or duplicate it: the abandoned blob's orphan is
        discarded wherever it landed)."""
        pbody = dict(body)
        pbody["phase"] = "prefill"
        pdata = json.dumps(pbody).encode()
        tried: List[str] = []
        # t1 = first prefill forward start: router_queue ends here;
        # prefill retries (rare) lump into prefill_device so the four
        # hops still partition the router's wall exactly
        t1: Optional[float] = None
        for attempt in range(self._max_retries + 1):
            replica, policy, ahit = self._pick(tokens, True, tried,
                                               role="prefill",
                                               steer=steer,
                                               adapter=adapter)
            if replica is None:
                if tried:
                    break
                return 503, {"Error": "no prefill replica in rotation"}
            if attempt:
                with self._lock:
                    self._retries += 1
                metrics.ROUTER_RETRIES.inc()
            if ctx is not None:
                pdata = json.dumps(
                    propagation.inject(pbody,
                                       propagation.child(ctx))).encode()
            if t1 is None:
                t1 = time.perf_counter()
                if t0 is not None:
                    metrics.REQUEST_HOP.observe(t1 - t0,
                                                hop="router_queue")
            with telemetry.span("router.prefill_forward", cat="router",
                                replica=replica.name,
                                trace=ctx.trace_id if ctx else None):
                out = self._forward_watched(replica, pdata)
            if out is not None and out[0] == 503 and isinstance(
                    out[1], dict) and "draining" in str(
                        out[1].get("Error", "")):
                # same ownership protocol as the balanced path: a
                # DRAINING refusal evicts with the draining reason (no
                # ownership-claiming drain of our own) and re-dispatches
                # — checked BEFORE the generic >=500 class, which would
                # otherwise swallow the 503
                self._evict(replica, "draining")
                tried.append(replica.name)
                continue
            if out is None or out[0] >= 500:
                if out is None:
                    self._note_failure(
                        replica, "abandoned (evicted mid-flight, "
                                 "transport error, or deadline)")
                tried.append(replica.name)
                continue
            code, payload = out[0], out[1]
            with self._lock:
                replica.requests += 1
                replica.consecutive_failures = 0
                if policy == "affinity" and not ahit:
                    replica.affinity_hits += 1
            metrics.ROUTER_REQUESTS.inc(replica=replica.name,
                                        policy=policy)
            if policy == "affinity" and not ahit:
                metrics.ROUTER_AFFINITY_HITS.inc(replica=replica.name)
            if ahit:
                metrics.ROUTER_ADAPTER_AFFINITY_HITS.inc(
                    replica=replica.name)
            if code != 200 or not isinstance(payload, dict) \
                    or "migration" not in payload:
                # a 4xx (the replica owns validation) or a request
                # that COMPLETED at activation — nothing to hand off
                # (headers relayed: a policy 429's Retry-After)
                return out
            # prefill succeeded with a blob to land: close the
            # prefill_device hop here so the hand-off owns the rest
            t2 = time.perf_counter()
            if t1 is not None:
                metrics.REQUEST_HOP.observe(t2 - t1,
                                            hop="prefill_device")
            return self._dispatch_handoff(replica, tokens, body,
                                          payload["migration"],
                                          steer=steer, adapter=adapter,
                                          ctx=ctx, t2=t2)
        return 502, {"Error": f"all prefill forwards failed "
                              f"(tried {', '.join(tried)})"}

    def _dispatch_handoff(self, prefill_r: Replica,
                          tokens: Optional[List[int]], body,
                          blob64: str, steer: bool = False,
                          adapter: Optional[str] = None,
                          ctx: "Optional[propagation.TraceContext]" = None,
                          t2: Optional[float] = None):
        """Land a prefilled session blob: decode replica, then the
        prefill replica itself (local decode), then re-prefill.
        ``t2`` (prefill completion) anchors the hand-off's two hops:
        the receiver reports its import+decode wall as ``served_s`` in
        the /migrate_in payload (popped below — never relayed to the
        client), decode_ttft = served_s, and migration_wire is the
        REMAINDER (t4 - t2 - served_s: blob transfer plus routing
        gap), so the hops sum to the router's wall; without served_s
        (an old replica) the split degrades to forward-start
        boundaries."""

        def mdata() -> bytes:
            # fresh child span per landing attempt, like the balanced
            # retry loop (the blob body is rebuilt per attempt anyway)
            mbody: dict = {"blob": blob64}
            if ctx is not None:
                mbody = propagation.inject(mbody, propagation.child(ctx))
            return json.dumps(mbody).encode()

        outcome, result, holder = None, None, None
        holder_policy, holder_ahit = "load", False
        t3: Optional[float] = None        # successful forward's start
        decode_r, dpolicy, dhit = self._pick(tokens, False, (),
                                             role="decode", steer=steer,
                                             adapter=adapter)
        if decode_r is not None:
            t3 = time.perf_counter()
            with telemetry.span("router.migrate_in_forward",
                                cat="router", replica=decode_r.name,
                                trace=ctx.trace_id if ctx else None):
                result = self._forward_watched(decode_r, mdata(),
                                               path="/migrate_in")
            if result is not None and result[0] == 200:
                outcome, holder = "ok", decode_r
                holder_policy, holder_ahit = dpolicy, dhit
            elif result is None:
                # wedged/evicted mid-transfer: the transport failure
                # class — the scrape loop owns the health verdict, but
                # this forward must not wait for it
                self._note_failure(
                    decode_r, "abandoned (evicted mid-flight, "
                              "transport error, or deadline)")
        if outcome is None:
            # receiver refused (pool full — counted receiver-side) or
            # died mid-transfer: LOCAL decode on the prefill replica,
            # whose pool held the session a moment ago
            with self._lock:
                prefill_r.inflight += 1   # _pick increments; mirror it
            t3 = time.perf_counter()
            with telemetry.span("router.migrate_in_forward",
                                cat="router", replica=prefill_r.name,
                                trace=ctx.trace_id if ctx else None):
                result = self._forward_watched(prefill_r, mdata(),
                                               path="/migrate_in")
            if result is not None and result[0] == 200:
                outcome, holder = "local_fallback", prefill_r
        if outcome is None:
            # the blob could not land anywhere: re-prefill from
            # scratch through the whole fleet (idempotent streams make
            # this safe; an orphan of the blob is discarded wherever
            # it landed, so no tokens duplicate)
            metrics.ROUTER_HANDOFFS.inc(outcome="reprefill")
            metrics.ROUTER_RETRIES.inc()
            with self._lock:
                self._retries += 1
            try:
                max_new = int(body.get("max_new_tokens", 32))
            except (TypeError, ValueError):
                max_new = 32
            return self._forward_balanced(
                body, tokens, self._prefill_heavy(tokens, max_new),
                steer=steer, adapter=adapter, ctx=ctx)
        metrics.ROUTER_HANDOFFS.inc(outcome=outcome)
        # close the hand-off hops: pop the receiver's served_s ALWAYS
        # (a measurement channel, not client payload), then split the
        # remaining wall into decode_ttft + migration_wire
        t4 = time.perf_counter()
        served = None
        if isinstance(result[1], dict):
            served = result[1].pop("served_s", None)
        if t2 is not None:
            remain = t4 - t2
            if isinstance(served, (int, float)) \
                    and 0.0 <= float(served) <= remain:
                metrics.REQUEST_HOP.observe(remain - float(served),
                                            hop="migration_wire")
                metrics.REQUEST_HOP.observe(float(served),
                                            hop="decode_ttft")
            elif t3 is not None:
                # no (or implausible) receiver report: fall back to
                # forward-start boundaries — still sums to the wall
                metrics.REQUEST_HOP.observe(t3 - t2,
                                            hop="migration_wire")
                metrics.REQUEST_HOP.observe(t4 - t3,
                                            hop="decode_ttft")
        with self._lock:
            holder.requests += 1
            holder.consecutive_failures = 0
            if holder_policy == "affinity" and not holder_ahit:
                holder.affinity_hits += 1
        metrics.ROUTER_REQUESTS.inc(replica=holder.name,
                                    policy=holder_policy)
        if holder_policy == "affinity" and not holder_ahit:
            metrics.ROUTER_AFFINITY_HITS.inc(replica=holder.name)
        if holder_ahit:
            metrics.ROUTER_ADAPTER_AFFINITY_HITS.inc(
                replica=holder.name)
        # the decode holder now owns the session's pages — future
        # same-prefix traffic should find them there (not for STEERED
        # requests: registering the spread target would drag the
        # over-share tenant's future traffic after its overflow)
        if not steer:
            self._repoint_affinity(tokens, holder.name, adapter=adapter)
        return result

    def _healthz(self, _body=None):
        with self._lock:
            up = sum(1 for r in self._replicas if r.in_rotation)
        body = {"state": "ok" if up else "no_replicas",
                "replicas_up": up, "replicas": len(self._replicas)}
        return (200, body) if up else (503, body)

    def _fleet(self, _body=None):
        """The authoritative per-replica view (inspect --fleet scrapes
        the /metrics series; this JSON carries the same numbers plus
        the scraped serving summaries for debugging)."""
        with self._lock:
            return 200, {
                "retries": self._retries,
                "policies": list(ROUTER_POLICIES),
                "over_share_tenants": sorted(self._over_share),
                "replicas": [r.view() for r in self._replicas],
            }

    # -- introspection (tests, bench) ----------------------------------
    def replica(self, name: str) -> Replica:
        for r in self._replicas:
            if r.name == name:
                return r
        raise KeyError(name)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpushare-router",
        description="Load-, prefix-, and health-aware request router "
                    "over N tpushare-llm-server replicas")
    ap.add_argument("replicas", nargs="*",
                    help="replica addresses, host:port "
                         "(optionally name=host:port)")
    ap.add_argument("--prefill-replicas", default="",
                    help="comma-separated PREFILL-role replicas "
                         "(host:port or name=host:port).  With "
                         "--decode-replicas this turns on prefill/"
                         "decode DISAGGREGATION: prompts prefill "
                         "here, then the KV-page session blob streams "
                         "to a decode replica's /migrate_in — a "
                         "prefill storm can no longer degrade decodes "
                         "(replicas need --slots and --page-size)")
    ap.add_argument("--decode-replicas", default="",
                    help="comma-separated DECODE-role replicas; see "
                         "--prefill-replicas")
    ap.add_argument("--port", type=int, default=8800)
    ap.add_argument("--addr", default="0.0.0.0")
    ap.add_argument("--no-affinity", action="store_true",
                    help="disable prefix-cache-affinity routing "
                         "(load + health only)")
    ap.add_argument("--prefix-block", type=int, default=16,
                    help="prefix-hash granularity in tokens; match the "
                         "replicas' --page-size so affinity hits map "
                         "to whole cached pages (default 16)")
    ap.add_argument("--scrape-interval", type=float, default=2.0,
                    help="seconds between /metrics + /healthz sweeps")
    ap.add_argument("--max-retries", type=int, default=2,
                    help="re-dispatch budget per request after "
                         "eviction/transport failure")
    ap.add_argument("--saturation", type=float, default=0.95,
                    help="batch occupancy at which an affinity target "
                         "is skipped in favor of the load policy")
    ap.add_argument("--request-timeout", type=float, default=600.0,
                    help="per-forward deadline before re-dispatch")
    ap.add_argument("--status-endpoints", default="",
                    help="comma-separated daemon /metrics addresses "
                         "(host:port) to scrape for per-tenant "
                         "share-vs-entitlement: requests whose body "
                         "names an over-share tenant (\"tenant\": "
                         "<pod>) steer to pure load routing — the "
                         "overflow spreads to under-loaded replicas "
                         "before the tenant is paced locally")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    def parse_specs(specs):
        out = []
        for spec in specs:
            spec = spec.strip()
            if not spec:
                continue
            if "=" in spec:
                name, _, address = spec.partition("=")
                out.append((name, address))
            else:
                out.append(spec)
        return out

    replicas = parse_specs(args.replicas)
    prefill = parse_specs(args.prefill_replicas.split(","))
    decode = parse_specs(args.decode_replicas.split(","))
    if not (replicas or (prefill and decode)):
        ap.error("pass replica addresses, or both --prefill-replicas "
                 "and --decode-replicas")
    router = FleetRouter(
        replicas, port=args.port, addr=args.addr,
        prefill_replicas=prefill, decode_replicas=decode,
        affinity=not args.no_affinity, prefix_block=args.prefix_block,
        scrape_interval_s=args.scrape_interval,
        max_retries=args.max_retries, saturation=args.saturation,
        request_timeout_s=args.request_timeout,
        status_endpoints=[e.strip()
                          for e in args.status_endpoints.split(",")
                          if e.strip()])
    log.info("router: %d replica(s) on :%d (affinity=%s, disagg=%s)",
             len(router._replicas), router.port, not args.no_affinity,
             router._disagg)
    router.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
