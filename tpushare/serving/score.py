"""Teacher-forced sequence scoring: per-token log-probabilities.

The eval-workload primitive (perplexity, reranking, answer scoring):
ONE forward over the whole sequence — the MXU-friendly way to score,
instead of decoding token by token.  Exposed over HTTP as the LLM
server's ``POST /score``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..models import transformer


@functools.partial(jax.jit, static_argnames=("cfg",))
def score_tokens(params, cfg: transformer.ModelConfig, tokens):
    """tokens [B, S] -> logprobs [B, S-1]: position i holds
    log P(tokens[:, i+1] | tokens[:, :i+1]).  f32 log-softmax over the
    f32-accumulated head logits (the same numerics the speculative
    verify path relies on)."""
    logits = transformer.forward(params, tokens[:, :-1], cfg)  # [B,S-1,V]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(
        logp, tokens[:, 1:, None], axis=-1)[..., 0]
