"""Greedy speculative decoding: draft proposes, target verifies.

The draft model decodes ``k`` tokens autoregressively (cheap), then the
target scores all of them in ONE forward (the MXU-friendly part: one
seq-k matmul pass instead of k sequential decode steps).  The longest
prefix where the draft agrees with the target's argmax is accepted, plus
the target's own next token at the first disagreement — so the output
is EXACTLY what plain greedy decoding of the target would produce, with
fewer target forwards whenever the draft is any good.

Static shapes throughout: both KV caches are fixed buffers; a rejection
just leaves the cache-length pointer behind (stale entries beyond it are
never attended thanks to position masking, and are overwritten by the
next proposal round).  The draft keeps its own fed-position counter and
catches up on accepted tokens it never processed, so its cache never has
holes.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..models import transformer
from .generate import make_decode_fns


@functools.partial(jax.jit, static_argnames=("cfg",))
def _verify(params, block, caches, pos, cfg):
    return transformer.forward(params, block, cfg, kv_caches=caches,
                               cache_len=pos)


@dataclasses.dataclass
class SpecStats:
    proposed: int = 0
    accepted: int = 0
    target_forwards: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


def speculative_generate(target_params, target_cfg: transformer.ModelConfig,
                         draft_params, draft_cfg: transformer.ModelConfig,
                         prompt: jnp.ndarray,
                         max_new_tokens: int = 32,
                         k: int = 4) -> Tuple[jnp.ndarray, SpecStats]:
    """prompt [1, P] -> ([1, P + max_new_tokens], stats); greedy-exact."""
    assert prompt.shape[0] == 1, "speculative path is per-sequence"
    p_len = prompt.shape[1]
    assert p_len + max_new_tokens <= min(target_cfg.max_seq,
                                         draft_cfg.max_seq)
    t_prefill, _ = make_decode_fns(target_cfg)
    d_prefill, d_step = make_decode_fns(draft_cfg)

    t_caches = transformer.init_kv_caches(target_cfg, 1)
    d_caches = transformer.init_kv_caches(draft_cfg, 1)
    t_logits, t_caches = t_prefill(target_params, prompt, t_caches, p_len)
    _, d_caches = d_prefill(draft_params, prompt, d_caches, p_len)
    stats = SpecStats(target_forwards=1)

    tokens = [int(prompt[0, i]) for i in range(p_len)]
    n_ctx = p_len         # tokens the TARGET cache covers
    d_pos = p_len         # tokens the DRAFT cache covers
    next_tok = int(jnp.argmax(t_logits[0]))

    def draft_feed(tok: int, pos: int):
        nonlocal d_caches
        log, d_caches = d_step(draft_params, jnp.asarray([tok], jnp.int32),
                               d_caches, pos)
        return int(jnp.argmax(log[0]))

    while len(tokens) - p_len < max_new_tokens:
        tokens.append(next_tok)
        if len(tokens) - p_len >= max_new_tokens:
            break

        # --- draft catches up on accepted tokens it never processed -----
        while d_pos < len(tokens) - 1:
            draft_feed(tokens[d_pos], d_pos)
            d_pos += 1

        budget = max_new_tokens - (len(tokens) - p_len)
        kk = min(k, budget)

        # --- draft proposes kk tokens after next_tok ---------------------
        proposal = []
        tok = next_tok
        for _ in range(kk):
            tok = draft_feed(tok, d_pos)
            d_pos += 1
            proposal.append(tok)
        stats.proposed += kk

        # --- target verifies next_tok + proposal in one forward ----------
        block = jnp.asarray([[next_tok] + proposal], jnp.int32)
        v_logits, t_caches = _verify(target_params, block, t_caches, n_ctx,
                                     target_cfg)
        stats.target_forwards += 1
        greedy = [int(t) for t in jnp.argmax(v_logits[0], axis=-1)]
        # greedy[i] = target's choice after seeing block[: i + 1]

        n_accept = 0
        while n_accept < kk and proposal[n_accept] == greedy[n_accept]:
            n_accept += 1
        stats.accepted += n_accept

        tokens.extend(proposal[:n_accept])
        old_ctx = n_ctx
        n_ctx += 1 + n_accept          # next_tok + accepted proposals
        # Draft cache validity: it fed next_tok + proposal[:kk-1], so its
        # longest prefix matching the accepted context covers
        # min(n_accept + 1, kk) entries; rewind to there — stale entries
        # beyond are never attended and get overwritten.
        d_pos = old_ctx + min(n_accept + 1, kk)
        # target's token at the first mismatch, or the bonus token when
        # everything was accepted (block has kk+1 logits)
        next_tok = greedy[n_accept]

    out = jnp.asarray([tokens[: p_len + max_new_tokens]], jnp.int32)
    return out, stats
