"""Greedy speculative decoding: draft proposes, target verifies.

The draft model decodes ``k`` tokens autoregressively (cheap), then the
target scores all of them in ONE forward (the MXU-friendly part: one
seq-k matmul pass instead of k sequential decode steps).  The longest
prefix where the draft agrees with the target's argmax is accepted, plus
the target's own next token at the first disagreement — so the output
is EXACTLY what plain greedy decoding of the target would produce, with
fewer target forwards whenever the draft is any good.

Static shapes throughout: both KV caches are fixed buffers; a rejection
just leaves the cache-length pointer behind (stale entries beyond it are
never attended thanks to position masking, and are overwritten by the
next proposal round).  The draft keeps its own fed-position counter and
catches up on accepted tokens it never processed, so its cache never has
holes.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..models import transformer
from . import metrics
from .generate import make_decode_fns


@functools.partial(jax.jit, static_argnames=("cfg",))
def _verify(params, block, caches, pos, cfg):
    return transformer.forward(params, block, cfg, kv_caches=caches,
                               cache_len=pos)


@dataclasses.dataclass
class SpecStats:
    proposed: int = 0
    accepted: int = 0
    target_forwards: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


def speculative_generate(target_params, target_cfg: transformer.ModelConfig,
                         draft_params, draft_cfg: transformer.ModelConfig,
                         prompt: jnp.ndarray,
                         max_new_tokens: int = 32,
                         k: int = 4) -> Tuple[jnp.ndarray, SpecStats]:
    """prompt [1, P] -> ([1, P + max_new_tokens], stats); greedy-exact."""
    assert prompt.shape[0] == 1, "speculative path is per-sequence"
    p_len = prompt.shape[1]
    assert p_len + max_new_tokens <= min(target_cfg.max_seq,
                                         draft_cfg.max_seq)
    t_prefill, _ = make_decode_fns(target_cfg)
    d_prefill, d_step = make_decode_fns(draft_cfg)

    t_caches = transformer.init_kv_caches(target_cfg, 1)
    d_caches = transformer.init_kv_caches(draft_cfg, 1)
    t_logits, t_caches = t_prefill(target_params, prompt, t_caches, p_len)
    _, d_caches = d_prefill(draft_params, prompt, d_caches, p_len)
    stats = SpecStats(target_forwards=1)

    tokens = [int(prompt[0, i]) for i in range(p_len)]
    n_ctx = p_len         # tokens the TARGET cache covers
    d_pos = p_len         # tokens the DRAFT cache covers
    next_tok = int(jnp.argmax(t_logits[0]))

    def draft_feed(tok: int, pos: int):
        nonlocal d_caches
        log, d_caches = d_step(draft_params, jnp.asarray([tok], jnp.int32),
                               d_caches, pos)
        return int(jnp.argmax(log[0]))

    while len(tokens) - p_len < max_new_tokens:
        tokens.append(next_tok)
        if len(tokens) - p_len >= max_new_tokens:
            break

        # --- draft catches up on accepted tokens it never processed -----
        while d_pos < len(tokens) - 1:
            draft_feed(tokens[d_pos], d_pos)
            d_pos += 1

        budget = max_new_tokens - (len(tokens) - p_len)
        kk = min(k, budget)

        # --- draft proposes kk tokens after next_tok ---------------------
        proposal = []
        tok = next_tok
        for _ in range(kk):
            tok = draft_feed(tok, d_pos)
            d_pos += 1
            proposal.append(tok)
        stats.proposed += kk
        metrics.SPEC_PROPOSED.inc(kk)

        # --- target verifies next_tok + proposal in one forward ----------
        block = jnp.asarray([[next_tok] + proposal], jnp.int32)
        v_logits, t_caches = _verify(target_params, block, t_caches, n_ctx,
                                     target_cfg)
        stats.target_forwards += 1
        greedy = [int(t) for t in jnp.argmax(v_logits[0], axis=-1)]
        # greedy[i] = target's choice after seeing block[: i + 1]

        n_accept = 0
        while n_accept < kk and proposal[n_accept] == greedy[n_accept]:
            n_accept += 1
        stats.accepted += n_accept
        metrics.SPEC_ACCEPTED.inc(n_accept)

        tokens.extend(proposal[:n_accept])
        old_ctx = n_ctx
        n_ctx += 1 + n_accept          # next_tok + accepted proposals
        # Draft cache validity: it fed next_tok + proposal[:kk-1], so its
        # longest prefix matching the accepted context covers
        # min(n_accept + 1, kk) entries; rewind to there — stale entries
        # beyond are never attended and get overwritten.
        d_pos = old_ctx + min(n_accept + 1, kk)
        # target's token at the first mismatch, or the bonus token when
        # everything was accepted (block has kk+1 logits)
        next_tok = greedy[n_accept]

    out = jnp.asarray([tokens[: p_len + max_new_tokens]], jnp.int32)
    return out, stats


# ---------------------------------------------------------------------------
# Fused prompt-lookup speculation: the whole loop on device
# ---------------------------------------------------------------------------
def propose_lookup(buf, buf_len, k: int, ngram: int):
    """THE prompt-lookup proposal, for one token row: the ``k`` tokens
    that followed the most recent strictly-earlier occurrence of the
    trailing ``ngram`` in ``buf[:buf_len]``.

    Returns ``(proposal [k], prop_len)`` — ``prop_len`` = how many
    proposal entries are real (0 when no earlier match).  One
    definition shared by the single-request while_loop and the batched
    serving :func:`spec_scan` (which vmaps it per slot), so a fix to
    the lookup reaches both paths.
    """
    S = buf.shape[0]
    W = S - ngram + 1
    tail = jax.lax.dynamic_slice(buf, (buf_len - ngram,), (ngram,))
    match = jnp.ones((W,), bool)
    for j in range(ngram):
        match &= buf[j:j + W] == tail[j]
    idx = jnp.arange(W)
    match &= idx <= buf_len - ngram - 1          # strictly earlier
    i_best = jnp.max(jnp.where(match, idx, -1))
    has = i_best >= 0
    start = jnp.clip(i_best + ngram, 0, S - k)
    proposal = jax.lax.dynamic_slice(buf, (start,), (k,))
    prop_len = jnp.where(
        has, jnp.clip(buf_len - (i_best + ngram), 0, k), 0)
    return proposal, prop_len



# ---------------------------------------------------------------------------
# The batched serving spec round (shared by every storage flavor)
# ---------------------------------------------------------------------------
def spec_scan(verify, sample, bufs, buf_lens, n_ctxs, next_toks,
              remainings, actives, temps, keys, tks, tps, storage,
              k: int, ngram: int, n_rounds: int, rich: bool):
    """``n_rounds`` of batched prompt-lookup speculation as ONE traced
    ``lax.scan`` — the round body shared by every storage flavor's
    jitted spec program (``continuous._tick_spec`` /
    ``_tick_mixed_spec`` and their paged twins), so the propose/verify/
    accept/commit logic cannot drift between pools.

    Per round, per slot: commit the pending known-correct token into
    the slot's token buffer, propose the ``k`` tokens that followed the
    most recent earlier occurrence of the trailing ``ngram``
    (:func:`propose_lookup` over the slot's OWN history — GREEDY slots
    only), verify pending+proposal in one ``[B, 1+k]`` forward via
    ``verify(blocks, n_ctxs, live, storage) -> (logits, storage)``,
    and accept the longest agreeing prefix.  SAMPLING slots ride the
    same forward as plain decode rows: their proposal lanes are dead
    weight the weight-bound forward absorbs (``prop_len`` forced 0, so
    they never accept), their next token samples from the block's
    position-0 logits — identical math to a fused decode step — and
    their PRNG keys walk the same one-split-per-round chain the fused
    scan performs, so interleaving spec rounds with plain ticks keeps
    sampled streams bit-identical too.

    Rejected proposal tokens are MASKED, never rewound: their K/V
    writes stay in storage past the committed length (each ``verify``
    is responsible for containing them — position masking on full-size
    pools and page tables, eviction slack on rings; see DESIGN.md
    "Speculation on paged pools") until the next round's block, which
    starts at the committed length, rewrites them with real tokens —
    append-only per committed position, which is what carries the int8
    exact-self-consistency contract over to speculation.

    Returns (bufs, buf_lens, n_ctxs, next_toks, produced, keys,
    accepts [n_rounds, B], spec_lives [n_rounds, B], storage):
    ``produced[i]`` counts tokens committed into row i's buf;
    ``accepts``/``spec_lives`` feed the per-round accept-depth
    histogram (a live greedy row's accepted count per round).
    """
    B = bufs.shape[0]
    rows = jnp.arange(B)
    greedy_rows = temps <= 0.0

    def round_(st, _):
        bufs, buf_lens, n_ctxs, next_toks, produced, keys, storage = st
        live = actives & (produced < remainings)             # [B] bool
        # -- commit the pending token ------------------------------
        upd = jax.vmap(lambda b, t, p: jax.lax.dynamic_update_slice(
            b, t[None], (p,)))
        bufs = jnp.where(live[:, None],
                         upd(bufs, next_toks, buf_lens), bufs)
        buf_lens = buf_lens + live
        produced = produced + live
        rem_after = remainings - produced                    # [B]

        # -- propose from each row's own history (the ONE lookup
        # definition, vmapped) -------------------------------------
        proposals, prop_lens = jax.vmap(
            propose_lookup, in_axes=(0, 0, None, None))(
                bufs, buf_lens, k, ngram)                    # [B,k],[B]
        # sampling rows never accept: zero proposal length keeps their
        # round a plain decode step riding the same dispatch
        prop_lens = jnp.where(greedy_rows, prop_lens, 0)

        # -- verify pending + proposal in one forward --------------
        blocks = jnp.concatenate([next_toks[:, None], proposals], axis=1)
        logits, storage = verify(blocks, n_ctxs, live, storage)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B,1+k]

        # -- sampling rows: the block's position-0 logits ARE the
        # decode step; one key split per round, the same deterministic
        # chain the fused decode scan walks -------------------------
        ks = jax.vmap(jax.random.split)(keys)            # [B,2]: next,sub
        sampled = sample(logits[:, 0], temps, ks[:, 1],
                         tks if rich else None, tps if rich else None)

        # -- longest agreeing prefix, bounded per row --------------
        agree = ((proposals == greedy[:, :k])
                 & (jnp.arange(k)[None, :] < prop_lens[:, None]))
        n_acc = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1),
                        axis=1)
        n_acc = jnp.clip(n_acc, 0, jnp.maximum(rem_after, 0))
        n_acc = jnp.where(live & greedy_rows, n_acc, 0)
        # append accepted proposals (the garbage tail beyond n_acc sits
        # past buf_len and is overwritten before it is ever read)
        bufs = jnp.where(live[:, None],
                         jax.vmap(lambda b, pr, p:
                                  jax.lax.dynamic_update_slice(
                                      b, pr, (p,)))(bufs, proposals,
                                                    buf_lens),
                         bufs)
        buf_lens = buf_lens + n_acc
        produced = produced + n_acc
        n_ctxs = n_ctxs + (1 + n_acc) * live
        nxt = jnp.where(greedy_rows, greedy[rows, n_acc], sampled)
        next_toks = jnp.where(live, nxt, next_toks)
        return ((bufs, buf_lens, n_ctxs, next_toks, produced, ks[:, 0],
                 storage),
                (n_acc, live & greedy_rows))

    produced0 = jnp.zeros((B,), jnp.int32)
    (bufs, buf_lens, n_ctxs, next_toks, produced, keys, storage), \
        (accepts, spec_lives) = jax.lax.scan(
            round_, (bufs, buf_lens, n_ctxs, next_toks, produced0, keys,
                     storage), None, length=n_rounds)
    return (bufs, buf_lens, n_ctxs, next_toks, produced, keys, accepts,
            spec_lives, storage)


@functools.lru_cache(maxsize=8)
def _make_lookup_spec(cfg: transformer.ModelConfig, prompt_len: int,
                      max_new: int, k: int, ngram: int):
    """Build the jitted device-resident lookup-speculative decoder.

    TPU-native speculative decoding: the draft is not a second model but
    PROMPT LOOKUP — propose the ``k`` tokens that followed the most
    recent earlier occurrence of the trailing ``ngram`` — and the entire
    propose/verify/accept loop runs in ONE jitted ``lax.while_loop``, so
    the host (and on a tunnel-attached chip, the ~70 ms RPC) is paid
    once per generation, not per round.  The win stacks two effects:

    * batch-1 decode is WEIGHT-bound, so verifying k+1 tokens in one
      forward costs about the same HBM traffic as decoding one token —
      accepted proposals are nearly free tokens;
    * the n-gram scan is a handful of vector compares over the token
      buffer — noise next to a forward.

    Output is EXACTLY greedy decoding of the model (the speculative
    contract); on text with repetition (code, logs, retrieval contexts —
    prompt-lookup's home turf) acceptance is high and tokens/s multiplies.
    """
    if prompt_len + max_new + k > cfg.max_seq:
        raise ValueError("prompt + max_new + k must fit max_seq")
    if ngram < 1 or k < 1:
        raise ValueError("ngram and k must be >= 1")
    S = cfg.max_seq
    W = S - ngram + 1            # candidate match positions

    @jax.jit
    def run(params, prompt):                       # prompt [1, P]
        logits, caches = transformer.forward(
            params, prompt, cfg,
            kv_caches=transformer.init_kv_caches(cfg, 1), cache_len=0)
        next_tok = jnp.argmax(logits[0, -1]).astype(jnp.int32)
        buf = jnp.zeros((S,), jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, prompt[0], (0,))

        def cond(st):
            return st[4] < max_new

        def body(st):
            buf, buf_len, n_ctx, next_tok, produced, caches, n_verify = st
            # commit the pending known-correct token
            buf = jax.lax.dynamic_update_slice(
                buf, next_tok[None], (buf_len,))
            buf_len = buf_len + 1
            produced = produced + 1
            remaining = max_new - produced

            def round_(op):
                buf, buf_len, n_ctx, next_tok, caches, n_verify = op
                # -- propose: most recent earlier match of the tail ----
                proposal, prop_len = propose_lookup(buf, buf_len, k, ngram)

                # -- verify next_tok + proposal in one forward ---------
                block = jnp.concatenate([next_tok[None], proposal]
                                        )[None, :]
                v_logits, caches = _verify(params, block, caches, n_ctx,
                                           cfg)
                greedy = jnp.argmax(v_logits[0], axis=-1).astype(jnp.int32)

                # -- longest agreeing prefix, bounded ------------------
                agree = (proposal == greedy[:k]) & (jnp.arange(k) < prop_len)
                n_acc = jnp.sum(jnp.cumprod(agree.astype(jnp.int32)))
                n_acc = jnp.minimum(n_acc, remaining - 1)
                n_acc = jnp.maximum(n_acc, 0)
                # append accepted proposals (garbage beyond n_acc lands
                # past buf_len and is overwritten before it matters)
                buf = jax.lax.dynamic_update_slice(buf, proposal,
                                                   (buf_len,))
                buf_len = buf_len + n_acc
                n_ctx = n_ctx + 1 + n_acc
                next_tok = greedy[n_acc]
                return buf, buf_len, n_ctx, next_tok, caches, n_verify + 1

            def done(op):
                return op

            buf, buf_len, n_ctx, next_tok, caches, n_verify = jax.lax.cond(
                remaining > 0, round_, done,
                (buf, buf_len, n_ctx, next_tok, caches, n_verify))
            # produced = committed tokens (next_tok commits + accepted
            # proposals), which is exactly how far buf has grown
            produced = buf_len - prompt_len
            return (buf, buf_len, n_ctx, next_tok, produced, caches,
                    n_verify)

        st = (buf, jnp.int32(prompt_len), jnp.int32(prompt_len), next_tok,
              jnp.int32(0), caches, jnp.int32(1))
        buf, buf_len, *_rest = jax.lax.while_loop(cond, body, st)
        n_verify = _rest[-1]
        return buf[None, :prompt_len + max_new], n_verify

    return run


def lookup_speculative_generate(params, cfg: transformer.ModelConfig,
                                prompt, max_new_tokens: int = 32,
                                k: int = 8, ngram: int = 2):
    """Greedy-exact prompt-lookup speculative decode, fully on device.

    prompt [1, P] -> ([1, P + max_new_tokens], n_target_forwards).
    See :func:`_make_lookup_spec`; outputs are bit-identical to
    :func:`tpushare.serving.generate.generate` (asserted in tests),
    with ``n_target_forwards <= max_new_tokens`` — well below it
    whenever the context repeats itself.
    """
    assert prompt.shape[0] == 1, "lookup speculation is per-sequence"
    run = _make_lookup_spec(cfg, int(prompt.shape[1]), int(max_new_tokens),
                            int(k), int(ngram))
    out, n_verify = run(params, prompt)
    return out, int(n_verify)
