"""Byte-level tokenizer: text ⇄ ids with zero external assets.

Serving needs *a* tokenizer out of the box (this environment cannot
download vocabularies); UTF-8 bytes offset past the special ids are the
simplest fully-reversible scheme.  Any model with ``vocab >= 258``
works; real deployments swap in their own tokenizer behind the same
two-method surface.
"""

from __future__ import annotations

from typing import List

PAD_ID = 0
BOS_ID = 1
_OFFSET = 2
VOCAB_FLOOR = 256 + _OFFSET


class ByteTokenizer:
    """ids = [BOS] + (utf8 byte + 2 per byte)."""

    def __init__(self, add_bos: bool = True):
        self.add_bos = add_bos

    def encode(self, text: str) -> List[int]:
        ids = [b + _OFFSET for b in text.encode("utf-8")]
        return ([BOS_ID] + ids) if self.add_bos else ids

    def decode(self, ids: List[int]) -> str:
        # specials (< _OFFSET) and ids beyond the byte range (a model may
        # have vocab > 258 and emit them) drop out
        data = bytes(i - _OFFSET for i in ids
                     if _OFFSET <= i < 256 + _OFFSET)
        return data.decode("utf-8", errors="replace")
