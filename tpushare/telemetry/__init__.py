"""Unified telemetry plane: metrics, tracing, health, flight recorder.

One process-global :data:`REGISTRY` (counters / gauges / fixed-bucket
histograms, Prometheus text rendering), one process-global
:data:`tracer` (bounded ring buffer of Chrome trace events), one
process-global flight :data:`recorder` (bounded ring of structured
forensics events, dumped at ``/debug/events`` and snapshotted to disk
on a WEDGED transition), and one backend health :data:`monitor`
(OK/DEGRADED/WEDGED/CPU_FALLBACK state machine + probe loop + dispatch
stall watchdog, served at ``/healthz``).  Both planes instrument
against these; the daemon's and the LLM server's endpoints serve them.

``set_enabled(False)`` turns every instrumentation site into a single
flag check (the near-free disabled path the overhead test pins down).
Stdlib only — importable from the device-plugin daemon, the inspect
CLI, and workload containers alike.
"""

import time as _time
from contextlib import contextmanager as _contextmanager

from .registry import (DEFAULT_LATENCY_BUCKETS, PROM_CONTENT_TYPE,  # noqa: F401
                       REGISTRY, Counter, Gauge, Histogram, Registry,
                       counter, enabled, gauge, histogram, parse_text,
                       quantile_from_buckets, set_enabled)
from .trace import TRACER as tracer  # noqa: F401
from .trace import Tracer  # noqa: F401
from .events import RECORDER as recorder  # noqa: F401
from .events import FlightRecorder  # noqa: F401
from . import health  # noqa: F401
from .health import MONITOR as monitor  # noqa: F401


def span(name: str, cat: str = "tpushare", **args):
    """Record a span on the global tracer (no-op context when disabled)."""
    return tracer.span(name, cat=cat, **args)


@_contextmanager
def timed(hist: Histogram, name: str, cat: str = "tpushare", **args):
    """One span + one histogram observation over the same wall-time
    window — the RPC instrumentation idiom (Allocate, kubelet queries),
    defined once so the two readings can never drift apart.  The
    histogram observes even when the body raises (failures count toward
    latency; they are the slow calls an operator is hunting)."""
    t0 = _time.perf_counter()
    with tracer.span(name, cat=cat, **args):
        try:
            yield
        finally:
            hist.observe(_time.perf_counter() - t0)
