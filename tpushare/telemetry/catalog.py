"""Metric catalog generator: the registry rendered as docs/METRICS.md.

Imports every instrumented module (the same set the metric-name lint
imports, so the two views cannot diverge), then renders one markdown
table row per registered family — name, type, declared labels, help.
``tests/test_metric_lint.py`` asserts the committed docs/METRICS.md
matches this render byte for byte (generated in a clean subprocess, so
test-registered families cannot leak in): a new metric without a
regenerated catalog fails CI, not a dashboard review.

Regenerate with::

    python -m tpushare.telemetry.catalog > docs/METRICS.md
"""

from __future__ import annotations

HEADER = """\
# tpushare metric catalog

Every metric family the instrumented modules register, as rendered by
`/metrics` on the daemon (control plane + per-tenant accounting) and
`tpushare-llm-server` (serving plane).  GENERATED — do not edit by
hand; regenerate with `python -m tpushare.telemetry.catalog >
docs/METRICS.md` (a test asserts this file matches the registry).

Conventions (enforced by tests/test_metric_lint.py): `tpushare_`
prefix; counters end `_total`; time histograms end `_seconds`; byte
gauges end `_bytes`; `_info` families are constant-1 gauges whose
payload rides the labels; label names come from the enumerated
allowlist and never carry request IDs or other unbounded values
(request IDs ride flight-recorder events instead).

| Metric | Type | Labels | Help |
|---|---|---|---|
"""


def _import_instrumented() -> None:
    """The modules whose import registers the full namespace (keep in
    sync with tests/test_metric_lint.py::_registered)."""
    import tpushare.inspect.metricsview  # noqa: F401
    import tpushare.kubelet.client  # noqa: F401
    import tpushare.plugin.allocate  # noqa: F401
    import tpushare.plugin.status  # noqa: F401
    import tpushare.serving.metrics  # noqa: F401
    import tpushare.telemetry.health  # noqa: F401


def render_catalog() -> str:
    _import_instrumented()
    from . import registry

    lines = [HEADER]
    for name, kind, help_text, labels in registry.REGISTRY.families():
        label_cell = ", ".join(f"`{l}`" for l in labels) if labels else "—"
        help_cell = " ".join(help_text.split()).replace("|", r"\|")
        lines.append(f"| `{name}` | {kind} | {label_cell} "
                     f"| {help_cell} |\n")
    return "".join(lines)


def main() -> int:
    print(render_catalog(), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
