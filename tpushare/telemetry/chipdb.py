"""Chip peak database: the denominators of the roofline cost plane.

One tiny table of published per-chip peaks — bf16 matmul FLOP/s, HBM
bandwidth, ICI (inter-chip interconnect) bandwidth — keyed by
accelerator-type substring, most specific first (the bench.py
``_PEAK_BF16`` idiom; bench now routes through here so the repo keeps
ONE peak table).  Sources: public TPU spec sheets, per chip.

Resolution order mirrors the C shim (native/tpushim.c):
``TPUSHIM_ACCELERATOR_TYPE`` wins — the test/generation override,
because the host rewrites ``TPU_ACCELERATOR_TYPE`` (CLAUDE.md) — then
``TPU_ACCELERATOR_TYPE``, then an explicit ``kind`` argument (e.g. a
jax ``device_kind`` string).  An unknown/absent type returns ``None``:
the roofline gauges are ABSENT on CPU or unrecognized chips, never
zero — a 0% MFU reading must mean "measured idle", not "no table row".

Stdlib only; importable before jax like the rest of the telemetry
plane.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional


class ChipPeaks(NamedTuple):
    """Published peaks for one chip generation (per chip, all cores)."""

    #: canonical generation name (the substring key that matched)
    generation: str
    #: bf16 matmul peak, FLOP/s
    flops_bf16: float
    #: HBM bandwidth, bytes/s
    hbm_bytes_per_s: float
    #: ICI bandwidth per chip (aggregate across links), bytes/s
    ici_bytes_per_s: float


#: (substring, peaks) — matched against the lowercased accelerator
#: type, MOST SPECIFIC FIRST ("v5p" before "v5"; "v5" covers
#: v5e / v5lite / v5litepod, the chip this repo's tunnel serves).
CHIP_PEAKS = (
    ("v6", ChipPeaks("v6", 918e12, 1640e9, 448e9)),     # Trillium
    ("v5p", ChipPeaks("v5p", 459e12, 2765e9, 600e9)),
    ("v5", ChipPeaks("v5", 197e12, 819e9, 200e9)),      # v5e / v5 lite
    ("v4", ChipPeaks("v4", 275e12, 1228e9, 300e9)),
    ("v3", ChipPeaks("v3", 123e12, 900e9, 100e9)),
    ("v2", ChipPeaks("v2", 45e12, 700e9, 62e9)),
)

#: env vars consulted, in order (shim precedence: the test override
#: beats the host-rewritten one)
ACCELERATOR_TYPE_ENVS = ("TPUSHIM_ACCELERATOR_TYPE",
                         "TPU_ACCELERATOR_TYPE")


def accelerator_type(kind: Optional[str] = None) -> Optional[str]:
    """The accelerator-type string to key peaks by: the explicit
    ``kind`` argument (a jax ``device_kind``, when the caller has a
    live backend) beats the env, which follows shim precedence."""
    if kind:
        return kind
    for env in ACCELERATOR_TYPE_ENVS:
        val = os.environ.get(env)
        if val:
            return val
    return None


def chip_peaks(kind: Optional[str] = None) -> Optional[ChipPeaks]:
    """Peaks for the resolved accelerator type, or ``None`` when the
    type is absent (CPU) or matches no table row (future chips refuse
    loudly-by-absence instead of reusing a stale generation's peaks)."""
    resolved = accelerator_type(kind)
    if not resolved:
        return None
    lowered = resolved.lower()
    for key, peaks in CHIP_PEAKS:
        if key in lowered:
            return peaks
    return None


def chip_peak_flops(kind: Optional[str] = None) -> Optional[float]:
    """bf16 peak FLOP/s alone (the bench.py MFU denominator)."""
    peaks = chip_peaks(kind)
    return peaks.flops_bf16 if peaks else None
