"""Bounded structured flight recorder (JSONL ring) for outage forensics.

The round-4 tunnel outage (CLAUDE.md "Environment hazards") was
reconstructed from scattered stderr lines; this module is the organized
replacement: every plane records small structured events — admissions,
dispatch begin/end with measured device residency, health transitions,
HBM grant/refusal, errors — into one fixed-capacity ring.  Like the
trace ring it is a RING, not a log: recording stays permanently on with
no I/O and bounded memory, and a dump shows the most recent window,
which is the window a post-mortem wants.

Two dump paths:

* on demand at ``/debug/events`` (daemon and ``tpushare-llm-server``),
  newline-delimited JSON, newest last;
* automatically to disk when the health monitor transitions to WEDGED
  (:mod:`tpushare.telemetry.health`) — by the time an operator notices a
  wedge the interesting events are minutes old, and a hung process may
  never answer an HTTP dump again.  The snapshot must therefore happen
  at the TRANSITION, from the watchdog thread, not from a handler.

Disabled-path contract: ``record()`` starts with the same single
module-global flag check every registry mutation starts with
(``telemetry.set_enabled(False)`` turns recording off).  Stdlib only.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import List, Optional

from . import registry

#: env override for where WEDGED snapshots land (default: the system
#: temp dir — a workload container may have no writable cwd)
SNAPSHOT_DIR_ENV = "TPUSHARE_FLIGHT_DIR"


def snapshot_dir() -> str:
    return os.environ.get(SNAPSHOT_DIR_ENV) or tempfile.gettempdir()


#: Lock-discipline manifest (tpushare.analysis.confinement): ring and
#: sequence mutations happen only under the recorder's own lock.
_LOCK_GUARDED = {
    "FlightRecorder": ("_buf", "_seq"),
}


class FlightRecorder:
    """Fixed-capacity deque of event dicts; thread-safe; JSONL dumps."""

    def __init__(self, capacity: int = 2048):
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._buf.maxlen or 0

    def set_capacity(self, capacity: int) -> None:
        # lock held around the swap: a concurrent record() must land in
        # either the old or the new deque, never in a dropped one
        with self._lock:
            self._buf = collections.deque(self._buf, maxlen=capacity)

    def record(self, kind: str, _ts: Optional[float] = None,
               **fields) -> int:
        """Append one event; returns its monotonically increasing ``seq``
        (0 when disabled — the caller's handle for correlating begin/end
        pairs, e.g. a dispatch stall pointing back at its begin event).
        ``fields`` must be JSON-serializable (they ride into dumps).
        ``_ts`` backdates the event (retroactive dispatch_begin records:
        the health plane emits a dispatch's begin lazily — at stall
        detection or slow-dispatch exit — stamped with the dispatch's
        TRUE start time, so the boring fast path records nothing)."""
        if not registry.enabled():
            return 0
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq,
                     "ts": round(_ts if _ts is not None else time.time(),
                                 6),
                     "kind": kind}
            event.update(fields)
            self._buf.append(event)
            return self._seq

    def events(self) -> List[dict]:
        """Snapshot, oldest first."""
        with self._lock:
            return list(self._buf)

    def events_since(self, seq: int) -> List[dict]:
        """Events with ``seq`` strictly greater than the cursor, oldest
        first — the incremental-tail read behind ``/debug/events?since=``
        (a scraper remembers the last seq it saw and re-fetches only the
        delta instead of re-downloading the whole ring).  A cursor that
        has fallen off the back of the ring simply returns the whole
        ring: the scraper lost events either way, and the seq gap tells
        it how many."""
        with self._lock:
            return [e for e in self._buf if e["seq"] > seq]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def to_jsonl(self, since: int = 0) -> str:
        events = self.events_since(since) if since else self.events()
        return "".join(json.dumps(e, sort_keys=True) + "\n"
                       for e in events)

    def snapshot_to(self, path: Optional[str] = None,
                    reason: str = "") -> Optional[str]:
        """Write the ring to ``path`` (default: a timestamped file in
        :func:`snapshot_dir`) as JSONL, preceded by one header line.
        Returns the path, or None when the write failed — forensics
        must never take down the process it is documenting."""
        if path is None:
            path = os.path.join(
                snapshot_dir(),
                f"tpushare_flight_{os.getpid()}_{int(time.time())}.jsonl")
        header = json.dumps({"kind": "snapshot_header", "pid": os.getpid(),
                             "ts": round(time.time(), 6),
                             "reason": reason}, sort_keys=True)
        try:
            with open(path, "w") as f:
                f.write(header + "\n")
                f.write(self.to_jsonl())
            return path
        except OSError:
            return None


#: the process-global flight recorder every plane records into
RECORDER = FlightRecorder()


from ..utils.httpserver import with_query  # noqa: E402 (stdlib-only)


@with_query
def debug_events_route(_body=None, query=None):
    """Drop-in JsonHTTPServer handler: GET /debug/events[?since=<seq>]
    off :data:`RECORDER` — whole ring by default, or only events with
    ``seq`` strictly greater than the cursor, so a scraper can TAIL the
    ring incrementally (remember the last seq seen, fetch the delta)
    instead of re-downloading 2048 events per poll.  One shared
    implementation for the daemon's status listener and the LLM server
    (the ``healthz_route`` pattern)."""
    from ..utils.httpserver import RawBody

    try:
        since = int((query or {}).get("since", 0))
    except (TypeError, ValueError):
        return 400, {"Error": "since must be an integer seq cursor"}
    return 200, RawBody(RECORDER.to_jsonl(since=since),
                        "application/x-ndjson")
