"""Backend health plane: state machine, probe loop, dispatch watchdog.

The axon backend is the least-observable component in the stack: a dead
tunnel stalls backend init for ~25 minutes, ``block_until_ready`` is a
false barrier, and a hung fetch blocks a worker thread forever
(CLAUDE.md "Environment hazards").  This module gives both planes ONE
shared answer to "is the backend OK, slow, or wedged":

* a four-state machine — ``OK / DEGRADED / WEDGED / CPU_FALLBACK`` —
  exported one-hot as ``tpushare_backend_health_state{state=...}`` plus
  a scalar ``tpushare_backend_up``, and served at ``/healthz`` (non-200
  exactly when WEDGED, so it can wire straight into a kubelet
  liveness/readiness probe);
* a low-frequency probe loop: a tiny compile+dispatch+SCALAR-FETCH with
  a deadline — the host fetch is the only reliable barrier on remote
  backends (never ``block_until_ready``); a probe that misses its
  deadline is ABANDONED to finish on its own, never killed (killing a
  process/thread mid-TPU-dial wedges the tunnel);
* a per-dispatch stall watchdog: serving wraps every device
  dispatch+fetch in :meth:`HealthMonitor.dispatch_guard`; a guard open
  past its deadline increments ``tpushare_dispatch_stalls_total``,
  transitions the machine to WEDGED, and snapshots the flight recorder
  to disk — while the hung worker keeps waiting untouched (the
  CLAUDE.md survival rule: marking, never killing);
* per-phase device-time attribution: guard exit observes
  ``tpushare_device_time_seconds{phase=prefill|decode|mixed}`` with the
  known constant tunnel-RPC overhead subtracted — the measured usage
  feedback SGDRC-style co-location decisions need;
* the tenant-policy choke point (round 19): an installed
  ``serving.policy.DispatchPacer`` (:meth:`HealthMonitor.
  install_policy`) is consulted on guard ENTER (``acquire(phase)`` —
  the pacing sleep, on the serving loop thread, before the timer) and
  fed on guard EXIT (``debit(phase, device_s)`` — the same measured
  residency the attribution records), turning the advisory device-time
  accounting into enforcement without a second dispatch path.

``bench.py``'s probe-deadline / CPU-fallback / stall-watchdog logic
lives here too (:func:`probe_platform`, :func:`start_stall_watchdog`)
so there is exactly one probe implementation in the tree.

Stdlib only at import; jax is imported lazily inside the default probe.
The disabled path (``telemetry.set_enabled(False)``) reduces every
entry point to one flag check.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from . import registry
from .events import RECORDER

# -- states ----------------------------------------------------------------
OK = "ok"
DEGRADED = "degraded"
WEDGED = "wedged"
CPU_FALLBACK = "cpu_fallback"
STATES = (OK, DEGRADED, WEDGED, CPU_FALLBACK)

#: dispatch phases with their own device-time series (the label values
#: tpushare_device_time_seconds carries; lint pins the histogram name)
PHASES = ("prefill", "decode", "mixed")

# -- metrics ---------------------------------------------------------------
BACKEND_UP = registry.gauge(
    "tpushare_backend_up",
    "1 when the accelerator backend is believed usable (OK/DEGRADED), "
    "0 when WEDGED or running on the CPU fallback")
HEALTH_STATE = registry.gauge(
    "tpushare_backend_health_state",
    "Backend health state machine, one-hot by the state label "
    "(ok/degraded/wedged/cpu_fallback; exactly one series is 1)",
    labels=("state",))
PROBE_LATENCY = registry.histogram(
    "tpushare_probe_latency_seconds",
    "Wall latency of backend health probes (tiny dispatch + scalar "
    "fetch, the true completion barrier); deadline misses observe the "
    "deadline")
DISPATCH_STALLS = registry.counter(
    "tpushare_dispatch_stalls_total",
    "Device dispatches still in flight past the stall deadline (the "
    "hung worker is marked, never killed)")
DEVICE_TIME = registry.histogram(
    "tpushare_device_time_seconds",
    "Measured per-dispatch device residency by phase (prefill/decode/"
    "mixed): wall time of dispatch+host-fetch minus the constant "
    "tunnel-RPC overhead (TPUSHARE_RPC_OVERHEAD_MS)",
    labels=("phase",))
DEVICE_UTILIZATION = registry.gauge(
    "tpushare_device_utilization",
    "Fraction of wall-clock time attributed to device compute across "
    "all phases (sum of tpushare_device_time_seconds over process "
    "uptime) — the live goodput gauge; multiply by the workload's "
    "FLOP/s-at-full-utilization to read MFU")

#: process epoch for the utilization denominator
_UTIL_T0 = time.monotonic()


def refresh_device_utilization(now: Optional[float] = None) -> Optional[float]:
    """Re-derive :data:`DEVICE_UTILIZATION` from the per-phase device-
    time histogram sums (called after ticks and at scrape time).  The
    gauge is strictly DERIVED — no second accounting to drift."""
    if not registry.enabled():
        return None
    busy = sum(DEVICE_TIME.sum(phase=p) for p in PHASES)
    elapsed = (now if now is not None else time.monotonic()) - _UTIL_T0
    if elapsed <= 0:
        return None
    util = min(1.0, busy / elapsed)
    DEVICE_UTILIZATION.set(util)
    return util

def recordable_device_utilization() -> Optional[float]:
    """The goodput value a bench/sweep RECORD should carry: the freshly
    re-derived utilization, rounded, or None on the sticky CPU fallback
    (there the number would describe the fallback host, not the
    accelerator the record is about).  One definition for bench.py and
    bench_all.py — the round-9 no-private-copies rule."""
    util = refresh_device_utilization()
    if util is None or MONITOR.state == CPU_FALLBACK:
        return None
    return round(util, 4)


#: the known constant per-dispatch RPC overhead of the tunnel-attached
#: chip, subtracted from wall time to attribute DEVICE residency
#: (CLAUDE.md: ~70 ms per dispatch through the tunnel; 0 when no tunnel)
RPC_OVERHEAD_ENV = "TPUSHARE_RPC_OVERHEAD_MS"

#: memoized rpc_overhead_s result — an os.environ read is ~1.5 µs,
#: real money on the per-dispatch guard-exit path (None = recompute)
_RPC_OVERHEAD_CACHE: Optional[float] = None


def rpc_overhead_s() -> float:
    global _RPC_OVERHEAD_CACHE
    if _RPC_OVERHEAD_CACHE is not None:
        return _RPC_OVERHEAD_CACHE
    ms = os.environ.get(RPC_OVERHEAD_ENV)
    if ms is not None:
        try:
            val = max(0.0, float(ms) / 1000.0)
        except ValueError:
            val = 0.0
    else:
        val = 0.070 if os.environ.get("PALLAS_AXON_POOL_IPS") else 0.0
    _RPC_OVERHEAD_CACHE = val
    return val


def reset_rpc_overhead_cache() -> None:
    """Re-read the env on next use (tests changing the override)."""
    global _RPC_OVERHEAD_CACHE
    _RPC_OVERHEAD_CACHE = None


class _NullGuard:
    """Shared no-op context for the disabled path."""

    __slots__ = ()

    #: disabled guards measured nothing (class attr: slots instances
    #: share it, callers read it uniformly after the with-block)
    device_s = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_GUARD = _NullGuard()


class _DispatchGuard:
    __slots__ = ("_mon", "phase", "deadline_s", "observe", "info", "_t0",
                 "device_s")

    def __init__(self, mon: "HealthMonitor", phase: str,
                 deadline_s: Optional[float], observe: bool, info: dict):
        self._mon = mon
        self.phase = phase
        self.deadline_s = deadline_s
        self.observe = observe
        self.info = info
        #: measured device residency of this dispatch, set at exit when
        #: the guard observed (None for async-dispatch-only guards and
        #: stalled dispatches) — the per-request attribution reads this
        #: after the with-block to split device time across the request
        #: IDs that rode the dispatch
        self.device_s: Optional[float] = None

    def __enter__(self):
        pol = self._mon._policy
        if pol is not None:
            # pre-dispatch pacing hook (tpushare/serving/policy.py):
            # sleeps the CALLING thread — the serving loop, before its
            # next round's dispatch — when the tenant is over its
            # device-time share.  Deliberately BEFORE the timer and
            # before the watchdog registration: paced wall time is
            # neither attributed as device time nor mistakable for a
            # stall, and the hook never touches a hung worker or a
            # jitted program.
            pol.acquire(self.phase)
        self._t0 = time.monotonic()
        self._mon._guard_enter(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._mon._guard_exit(self, time.monotonic() - self._t0,
                              error=exc is not None)
        return False


#: Lock-discipline manifest — verified statically by
#: ``tpushare.analysis.confinement`` (Layer 3 of ``make lint``): every
#: MUTATION of these :class:`HealthMonitor` attributes outside
#: ``__init__`` must sit inside ``with self._lock:`` (methods whose
#: name ends in ``_locked`` are the documented callers-hold-the-lock
#: exception, registry.py style).  The public float knobs
#: (``dispatch_deadline_s``, ``slow_record_s``) and the probe-loop
#: lifecycle handles (``_probe_thread``, ``_probe_halt``) stay out:
#: the knobs are single-word reads the guards sample once, and the
#: probe loop is started/stopped by one owner.
_LOCK_GUARDED = {
    "HealthMonitor": ("state", "reason", "last_snapshot_path",
                      "_transitions", "_inflight", "_next_token",
                      "_scanner", "_policy"),
}


class HealthMonitor:
    """The process-global backend health state machine.

    Thread-safe; every mutating entry point is gated on the telemetry
    flag.  One instance (:data:`MONITOR`) is shared by the serving
    plane, the daemon status endpoint, the LLM server, and the bench
    harnesses — health is a property of the PROCESS's backend, so there
    is nothing per-component about it.
    """

    def __init__(self, dispatch_deadline_s: Optional[float] = None):
        self._lock = threading.Lock()
        if dispatch_deadline_s is None:
            dispatch_deadline_s = float(
                os.environ.get("TPUSHARE_DISPATCH_DEADLINE_S", "600"))
        #: in-flight dispatch deadline; guards may override per call.
        #: <= 0 disables stall watching entirely.
        self.dispatch_deadline_s = dispatch_deadline_s
        #: a clean dispatch slower than this still earns a dispatch_end
        #: flight event (slow-but-not-stalled is forensics too)
        self.slow_record_s = float(
            os.environ.get("TPUSHARE_SLOW_DISPATCH_RECORD_S", "1.0"))
        self.state = OK
        self.reason = "no probe yet"
        self.last_snapshot_path: Optional[str] = None
        self._transitions = 0
        self._inflight: Dict[int, dict] = {}   # seq -> guard record
        self._next_token = 0
        self._scanner: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_halt = threading.Event()
        #: installed tenant policy (serving/policy.py DispatchPacer or
        #: None): the dispatch guard's pre-dispatch pacing hook and
        #: post-dispatch device-time debit consult it.  One single-
        #: word read per guard — the disarmed path stays free.
        self._policy = None
        self._mirror_state()

    # -- state machine -------------------------------------------------
    def _mirror_state(self) -> None:
        for s in STATES:
            HEALTH_STATE.set(1.0 if s == self.state else 0.0, state=s)
        BACKEND_UP.set(1.0 if self.state in (OK, DEGRADED) else 0.0)

    def set_state(self, state: str, reason: str = "") -> None:
        """Transition (no-op when already there); WEDGED entry snapshots
        the flight recorder to disk — a hung process may never answer an
        HTTP dump, so forensics are written at the transition."""
        if state not in STATES:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            if state == self.state:
                self.reason = reason or self.reason
                return
            prev, self.state = self.state, state
            self.reason = reason
            self._transitions += 1
            self._mirror_state()
        RECORDER.record("health_transition", frm=prev, to=state,
                        reason=reason)
        if state == WEDGED:
            # the snapshot write (disk I/O) stays OUTSIDE the lock —
            # /healthz must answer while forensics flush — only the
            # path publication takes it
            path = RECORDER.snapshot_to(reason=f"WEDGED: {reason}")
            with self._lock:
                self.last_snapshot_path = path

    def mark_cpu_fallback(self, reason: str) -> None:
        """This process pinned the CPU backend (probe failure, backend
        init error).  STICKY: later probe successes describe the
        accelerator, not this process, which stays on CPU."""
        self.set_state(CPU_FALLBACK, reason)

    def healthz(self) -> Tuple[int, object]:
        """(status code, body) for a /healthz route: non-200 exactly
        when WEDGED, so the route can back a kubelet liveness probe
        (DEGRADED and CPU_FALLBACK still serve — restarting them fixes
        nothing and loses the flight recorder)."""
        with self._lock:
            state, reason = self.state, self.reason
            stalled = sum(1 for g in self._inflight.values()
                          if g.get("stalled"))
        if state == OK:
            return 200, "ok\n"
        body = {"state": state, "reason": reason,
                "stalled_dispatches": stalled}
        return (503, body) if state == WEDGED else (200, body)

    def snapshot(self) -> dict:
        """Point-in-time view for /healthz bodies, inspect, and tests."""
        with self._lock:
            return {"state": self.state, "reason": self.reason,
                    "inflight_dispatches": len(self._inflight),
                    "transitions": self._transitions,
                    "last_snapshot_path": self.last_snapshot_path}

    def reset(self) -> None:
        """Back to OK and forget in-flight guards — TEST isolation only
        (a live process has no legitimate amnesia)."""
        with self._lock:
            self.state, self.reason = OK, "reset"
            self._inflight.clear()
            self._transitions = 0
            self.last_snapshot_path = None
            self._policy = None
            self._mirror_state()

    # -- tenant policy hook --------------------------------------------
    def install_policy(self, policy) -> None:
        """Arm the dispatch guard's pacing hook with a
        ``serving.policy.DispatchPacer`` (or anything exposing
        ``acquire(phase)`` / ``debit(phase, device_s)``).  One policy
        per process — the entitlement is per-tenant-process, exactly
        like the health machine itself."""
        with self._lock:
            self._policy = policy

    def uninstall_policy(self, policy=None) -> None:
        """Disarm pacing.  Pass the policy you installed to make the
        call idempotent against a later owner (a stopping service must
        not disarm its successor's pacer)."""
        with self._lock:
            if policy is None or self._policy is policy:
                self._policy = None

    # -- probes --------------------------------------------------------
    def record_probe(self, ok: bool, latency_s: float,
                     reason: str = "", timed_out: bool = False) -> None:
        """Feed one probe result into the machine.  Timeout failures go
        straight to WEDGED (the round-4 outage signature: init/dispatch
        hanging ~1500 s); other failures mark DEGRADED.  Success
        recovers WEDGED/DEGRADED to OK but never un-pins CPU_FALLBACK."""
        if not registry.enabled():
            return
        PROBE_LATENCY.observe(latency_s)
        RECORDER.record("probe", ok=ok, latency_s=round(latency_s, 6),
                        reason=reason or None)
        if ok:
            recovered = False
            with self._lock:
                any_stalled = any(r["stalled"]
                                  for r in self._inflight.values())
                if any_stalled:
                    # Small RPCs answering while a real dispatch is
                    # still hung is the tunnel's classic half-dead
                    # mode: the probe must not paint a wedged machine
                    # green (the stall record never re-fires — see
                    # _scan_loop's not-stalled filter).
                    self.reason = ("probe ok but a stalled dispatch is "
                                   "still in flight")
                elif self.state in (DEGRADED, WEDGED):
                    recovered = True     # transition takes the lock itself
                elif self.state == OK:
                    self.reason = "probe ok"
            if recovered:
                self.set_state(OK, "probe recovered")
        elif timed_out:
            self.set_state(WEDGED, reason or "probe deadline exceeded")
        else:
            self.set_state(DEGRADED, reason or "probe failed")

    def start_probe_loop(self, probe_fn: Optional[Callable[[], None]] = None,
                         interval_s: float = 30.0,
                         deadline_s: float = 10.0) -> None:
        """Low-frequency background probing.  ``probe_fn`` performs one
        tiny dispatch and SCALAR-FETCHES the result (the true barrier);
        default :func:`jax_scalar_probe`.  Each probe runs in its own
        worker thread with ``deadline_s`` to finish; a late worker is
        abandoned (never killed) and its eventual result still lands —
        that late success is exactly how a recovered tunnel flips the
        machine back to OK without anyone re-arming anything."""
        if probe_fn is None:
            probe_fn = jax_scalar_probe
        self.stop_probe_loop()
        self._probe_halt = threading.Event()
        halt = self._probe_halt

        def probe_once():
            done = threading.Event()

            def worker():
                t0 = time.monotonic()
                try:
                    probe_fn()
                except Exception as e:
                    done.set()
                    self.record_probe(False, time.monotonic() - t0,
                                      f"probe raised {type(e).__name__}: "
                                      f"{str(e)[:200]}")
                    return
                done.set()
                self.record_probe(True, time.monotonic() - t0)

            t = threading.Thread(target=worker, daemon=True,
                                 name="tpushare-health-probe-worker")
            t.start()
            if not done.wait(deadline_s):
                # Mark now; the worker stays untouched and reports late.
                self.record_probe(False, deadline_s,
                                  "probe deadline exceeded (worker "
                                  "abandoned, not killed)",
                                  timed_out=True)

        def loop():
            while not halt.wait(interval_s):
                if registry.enabled():
                    probe_once()

        self._probe_thread = threading.Thread(
            target=loop, daemon=True, name="tpushare-health-probe")
        self._probe_thread.start()

    def stop_probe_loop(self) -> None:
        self._probe_halt.set()
        self._probe_thread = None

    # -- per-dispatch stall watchdog ----------------------------------
    def dispatch_guard(self, phase: str,
                       deadline_s: Optional[float] = None,
                       observe: bool = True, **info):
        """Context manager around ONE device dispatch (+ its host
        fetch).  On exit, observes per-phase device time (wall minus
        the constant tunnel-RPC overhead) into
        ``tpushare_device_time_seconds`` when ``observe`` (dispatch-only
        sites that fetch later pass ``observe=False`` so the near-zero
        async-dispatch wall time does not pollute the attribution).
        While open past the deadline, the watchdog marks a stall —
        counter + WEDGED + flight snapshot — without touching the
        blocked thread."""
        if not registry.enabled():
            return _NULL_GUARD
        return _DispatchGuard(self, phase, deadline_s, observe, info)

    def _guard_enter(self, g: _DispatchGuard) -> None:
        # HOT PATH: no recorder write here — the begin event is emitted
        # LAZILY (by the scanner at stall detection, or at exit for
        # slow/errored dispatches) backdated to rec["ts"], so a fast
        # clean dispatch costs one lock'd dict insert and the ring
        # keeps minutes of interesting history instead of seconds of
        # boring begin/end pairs.
        deadline = (g.deadline_s if g.deadline_s is not None
                    else self.dispatch_deadline_s)
        rec = {"begin_seq": 0, "phase": g.phase,
               "t0": time.monotonic(), "ts": time.time(),
               "deadline_s": deadline, "stalled": False,
               "info": g.info}
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._inflight[token] = rec
            g.info["_token"] = token
            if deadline and deadline > 0 and self._scanner is None:
                self._scanner = threading.Thread(
                    target=self._scan_loop, daemon=True,
                    name="tpushare-dispatch-watchdog")
                self._scanner.start()

    @staticmethod
    def _emit_begin(rec: dict) -> int:
        """Emit ``rec``'s retroactive dispatch_begin (idempotent)."""
        if not rec["begin_seq"]:
            info = {k: v for k, v in rec["info"].items()
                    if k != "_token"}
            rec["begin_seq"] = RECORDER.record(
                "dispatch_begin", _ts=rec["ts"], phase=rec["phase"],
                **info)
        return rec["begin_seq"]

    def _guard_exit(self, g: _DispatchGuard, wall_s: float,
                    error: bool) -> None:
        # HOT PATH: one guard per serving dispatch, against ms-scale
        # device work — stays a few µs.  The boring case (fast, clean,
        # machine OK) does: lock'd pop, one histogram observe, return.
        # dispatch_end flight events are recorded only when INTERESTING
        # (stalled / errored / slow): normal traffic would both cost
        # time and evict the events a post-mortem actually wants from
        # the bounded ring; the begin event (always recorded) plus the
        # per-phase histograms carry the steady-state story.
        token = g.info.pop("_token", None)
        with self._lock:
            rec = self._inflight.pop(token, None)
        stalled = bool(rec and rec["stalled"])
        if g.observe and not stalled:
            # a stalled dispatch's wall is tunnel hang, not device
            # compute — attributing it would pin the goodput gauge at
            # "fully busy" during exactly the hours it was zero
            g.device_s = max(0.0, wall_s - rpc_overhead_s())
            DEVICE_TIME.observe(g.device_s, phase=g.phase)
            pol = self._policy
            if pol is not None:
                # the same measured residency the attribution uses
                # drains the pacing bucket — one cost definition
                pol.debit(g.phase, g.device_s)
        if not (stalled or error or wall_s >= self.slow_record_s
                or self.state in (WEDGED, DEGRADED)):
            # WEDGED/DEGRADED traffic is forensics; sticky CPU_FALLBACK
            # is not — recording every CPU dispatch forever would flood
            # the ring and evict the history a post-mortem wants
            return
        begin_seq = self._emit_begin(rec) if rec else 0
        RECORDER.record("dispatch_end", phase=g.phase,
                        begin_seq=begin_seq, wall_s=round(wall_s, 6),
                        stalled=stalled, error=error, **g.info)
        if error:
            RECORDER.record("error", phase=g.phase,
                            wall_s=round(wall_s, 6))
        with self._lock:
            any_stalled = any(r["stalled"]
                              for r in self._inflight.values())
        if stalled and not any_stalled and self.state == WEDGED:
            # The hung dispatch came back (tunnel recovered on its own):
            # not OK yet — DEGRADED until a probe or further clean
            # traffic says otherwise — but no longer wedged.
            self.set_state(
                DEGRADED,
                f"stalled {g.phase} dispatch returned after "
                f"{wall_s:.1f}s")
        elif (not error and not stalled and self.state == DEGRADED
                and not any_stalled):
            self.set_state(OK, "clean dispatch after degradation")

    def _scan_loop(self) -> None:
        while True:
            with self._lock:
                deadlines = [r["deadline_s"]
                             for r in self._inflight.values()
                             if r["deadline_s"] and r["deadline_s"] > 0]
                floor = min(deadlines) if deadlines \
                    else (self.dispatch_deadline_s or 1.0)
            time.sleep(min(max(floor / 4.0, 0.02), 2.0))
            now = time.monotonic()
            newly = []
            with self._lock:
                for rec in self._inflight.values():
                    if (not rec["stalled"] and rec["deadline_s"]
                            and rec["deadline_s"] > 0
                            and now - rec["t0"] > rec["deadline_s"]):
                        rec["stalled"] = True
                        # the stalled dispatch's begin event (backdated
                        # to its true start) lands BEFORE the stall
                        # event — and, transitively, before the WEDGED
                        # snapshot; emitted under the lock so the exit
                        # path cannot double-emit it
                        self._emit_begin(rec)
                        newly.append(rec)
            for rec in newly:
                DISPATCH_STALLS.inc()
                RECORDER.record(
                    "dispatch_stall", phase=rec["phase"],
                    begin_seq=rec["begin_seq"],
                    waited_s=round(now - rec["t0"], 3),
                    deadline_s=rec["deadline_s"])
                self.set_state(
                    WEDGED,
                    f"{rec['phase']} dispatch in flight "
                    f"{now - rec['t0']:.1f}s > deadline "
                    f"{rec['deadline_s']:.1f}s (worker left running)")


#: the process-global monitor every plane consults
MONITOR = HealthMonitor()


def healthz_route(_body=None) -> Tuple[int, object]:
    """Drop-in JsonHTTPServer handler: GET /healthz off :data:`MONITOR`."""
    return MONITOR.healthz()


#: the probe's jitted program, built ONCE per process: a fresh lambda
#: per probe would re-jit (and re-remote_compile) every interval
_PROBE_FN = None


def jax_scalar_probe() -> None:
    """The default probe body: one tiny jitted dispatch whose result is
    host-fetched as a scalar — the only reliable completion barrier on
    the axon backend (``block_until_ready`` has returned early there).
    bf16 on purpose: f32 compiles through the tunnel are banned
    (CLAUDE.md — an f32 program hung remote_compile ~50 min), and the
    probe must never itself be the outage."""
    global _PROBE_FN
    import jax
    import jax.numpy as jnp

    if _PROBE_FN is None:
        _PROBE_FN = jax.jit(lambda x: x * 2 + 1)
    y = _PROBE_FN(jnp.bfloat16(1.0))
    assert float(y) == 3.0


# -------------------------------------------------------------------------
# Bench-side helpers (the ONE probe/watchdog implementation; bench.py and
# bench_all.py call these instead of carrying private copies)
# -------------------------------------------------------------------------

#: watchdog stages during which the process must NOT exit: the worker is
#: mid-TPU-dial, and exiting is exactly the kill CLAUDE.md bans
DIAL_STAGES = ("probe", "import-jax")


def probe_platform(deadline_s: float, log=lambda msg: None
                   ) -> Tuple[Optional[str], Optional[str]]:
    """Ask a SUBPROCESS what platform jax lands on, with a deadline.

    Only dials when the tunnel hook env (``PALLAS_AXON_POOL_IPS``) is
    present — that is the one case where backend init can stall ~25
    minutes.  The subprocess inherits the env, reproducing exactly the
    dial the caller would make.  Returns ``(platform, None)`` on
    success and ``(None, reason)`` on timeout/death (caller should pin
    cpu and :meth:`HealthMonitor.mark_cpu_fallback` with the reason).
    On timeout the subprocess is ABANDONED to exit on its own — never
    killed mid-dial.  Results feed :data:`MONITOR`.
    """
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return os.environ.get("JAX_PLATFORMS") or "local", None  # no dial
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return "cpu", None  # pinned; nothing to probe
    log(f"probing accelerator (deadline {deadline_s:.0f}s)...")
    t0 = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        out, _ = proc.communicate(timeout=deadline_s)
        lines = (out or "").strip().splitlines()
        if lines:
            MONITOR.record_probe(True, time.monotonic() - t0)
            return lines[-1], None
        log("probe subprocess exited without a platform (backend init "
            "crashed); falling back to cpu")
        reason = ("accelerator probe subprocess died without "
                  "initializing a backend; cpu fallback")
        MONITOR.record_probe(False, time.monotonic() - t0, reason)
        return None, reason
    except subprocess.TimeoutExpired:
        log("probe deadline hit; abandoning probe (not killing mid-dial) "
            "and falling back to cpu")
        reason = ("accelerator probe hit its deadline (tunnel outage "
                  "signature); cpu fallback - see CLAUDE.md "
                  "'Environment hazards'")
        MONITOR.record_probe(False, deadline_s, reason, timed_out=True)
        return None, reason


def resolve_platform():
    """jax.devices() with the standard CPU-fallback-on-init-failure
    policy (bench_all, probes): a failed backend init pins cpu and marks
    :data:`MONITOR` CPU_FALLBACK instead of raising."""
    import jax

    try:
        return jax.devices()[0].platform
    except RuntimeError as e:
        MONITOR.mark_cpu_fallback(
            f"backend init failed ({str(e)[:120]}); cpu fallback")
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform


def start_stall_watchdog(budget_s: float, state: dict, defaults: dict,
                         log=lambda msg: None,
                         emit=None, _exit=os._exit) -> threading.Thread:
    """Emit a degraded-but-valid record and exit if a bench run stalls.

    A tunnel fetch can hang FOREVER mid-measure (round 4: a streamed
    measurement blocked >25 min), and a blocked gRPC recv cannot be
    interrupted from Python.  The driver would eventually kill the
    process anyway — this watchdog beats it to the punch with whatever
    numbers exist so far.  ``state['best']`` is the best record
    assembled so far; ``state['stage'] == 'done'`` disarms.  The record
    gains ``degraded`` + ``health_state`` (the machine goes WEDGED,
    which also snapshots the flight recorder).  When the stall happens
    in a :data:`DIAL_STAGES` stage, the process is left alive — exiting
    mid-dial is exactly the kill that wedges the tunnel.
    """
    import json

    if emit is None:
        emit = lambda rec: print(json.dumps(rec), flush=True)

    def run():
        t0 = time.monotonic()
        while time.monotonic() - t0 < budget_s:
            time.sleep(5)
            if state.get("stage") == "done":
                return
        stage = state.get("stage")
        if stage == "done":
            return
        reason = (f"watchdog fired at stage {stage!r} after "
                  f"{budget_s:.0f}s (hung tunnel fetch?)")
        MONITOR.set_state(WEDGED, reason)
        rec = dict(state.get("best") or {})
        for k, v in defaults.items():
            rec.setdefault(k, v)
        rec["degraded"] = reason
        rec["health_state"] = MONITOR.state
        rec["health_reason"] = MONITOR.reason
        log(f"WATCHDOG: stalled at {stage!r}; emitting degraded record")
        emit(rec)
        if stage in DIAL_STAGES:
            log("WATCHDOG: stage is mid-dial; NOT exiting (record "
                "emitted; kill policy is the caller's)")
            return
        _exit(2)

    t = threading.Thread(target=run, daemon=True,
                         name="tpushare-bench-watchdog")
    t.start()
    return t
