"""Trace-context propagation: the ONE wire format for cross-process
traces (router -> replica -> migration receiver).

Rounds 15-16 made a request's path span processes; the ring tracer
(:mod:`tpushare.telemetry.trace`) and the rid attribution stop at the
process boundary.  This module owns the boundary crossing: a
W3C-traceparent-style context (``00-<32 hex trace_id>-<16 hex
span_id>-01``) rides a JSON-body field on every forwarded request and
inside the migration session header, so every process's spans and
flight-recorder events carry the SAME ``trace_id`` and the fleet
scraper (``kubectl inspect tpushare --trace``) can merge them into one
timeline.

Confinement mirrors the migration codec: the ``trace-wire-confinement``
tpulint rule keeps every traceparent parse/format inside this module —
the serving plane threads opaque ``trace_id`` strings, never the wire
encoding.  A body field rather than an HTTP header because
:class:`tpushare.utils.httpserver.JsonHTTPServer` routes hand handlers
the parsed body only (headers never reach them), and because the
migration blob's session meta is JSON either way.

Parse failures are SILENT (``None``): tracing is observability, and a
malformed context from an old client must never 400 a request that
would otherwise serve.  Stdlib only, pre-jax importable (the router
imports this before any backend exists; lint rule ``router-no-jax``
covers it).
"""

from __future__ import annotations

import os
import re
from typing import NamedTuple, Optional

#: the JSON-body field the context rides in (/generate forwards,
#: /migrate_in hand-offs) — one name everywhere, so the fake replica,
#: the router, and the LLM server cannot drift
TRACEPARENT_FIELD = "traceparent"

#: the critical-path hops of one disaggregated request, the enumerated
#: values of ``tpushare_request_hop_seconds{hop=}`` (enum-pinned in
#: tests/test_metric_lint.py).  ``router_queue`` = receipt to first
#: forward (both routing paths); the other three decompose the
#: disaggregated path: ``prefill_device`` = the prefill forward's wall,
#: ``decode_ttft`` = the decode replica's reported import+decode wall
#: (one-shot delivery: TTFT is the full serve, the repo-wide
#: convention), ``migration_wire`` = the hand-off remainder (blob
#: transfer + routing gap), so the four hops SUM to the router's
#: measured request wall.
REQUEST_HOPS = ("router_queue", "prefill_device", "migration_wire",
                "decode_ttft")

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


class TraceContext(NamedTuple):
    """One hop's view of a trace: the fleet-wide ``trace_id`` plus this
    hop's ``span_id`` (the downstream process's parent)."""

    trace_id: str
    span_id: str


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def new_context() -> TraceContext:
    """A fresh root context (the router mints one per request that
    arrives without a ``traceparent`` field)."""
    return TraceContext(new_trace_id(), new_span_id())


def child(ctx: TraceContext) -> TraceContext:
    """Same trace, fresh span id — one per forward ATTEMPT, so a retry's
    spans are distinguishable from the attempt they replaced."""
    return TraceContext(ctx.trace_id, new_span_id())


def format_traceparent(ctx: TraceContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value) -> Optional[TraceContext]:
    """Strict parse of the wire string; None for anything malformed
    (wrong version, casing, length — silently untraced, never a 400)."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value)
    if m is None:
        return None
    return TraceContext(m.group(1), m.group(2))


def extract(body) -> Optional[TraceContext]:
    """The context a request body carries, or None."""
    if not isinstance(body, dict):
        return None
    return parse_traceparent(body.get(TRACEPARENT_FIELD))


def inject(body: dict, ctx: TraceContext) -> dict:
    """Return a copy of ``body`` carrying ``ctx`` (the caller's dict is
    never mutated — retry loops re-inject a fresh child per attempt)."""
    out = dict(body)
    out[TRACEPARENT_FIELD] = format_traceparent(ctx)
    return out
