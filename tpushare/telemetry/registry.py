"""Dependency-free, thread-safe metrics registry (Prometheus text format).

The telemetry substrate both planes instrument against (DESIGN.md
"telemetry plane"): counters, gauges, and fixed-bucket histograms in one
process-global registry, rendered in the Prometheus text exposition
format (``# HELP``/``# TYPE`` metadata per family, escaped labels,
cumulative ``_bucket``/``_sum``/``_count`` series for histograms).

Design constraints, in order:

* **stdlib only** — the workload containers and the daemon both import
  this; neither may grow a dependency;
* **near-free when disabled** — every mutating op starts with one module
  -global flag check and returns, so instrumentation can stay inline in
  the serving hot path permanently (the overhead test pins the enabled
  path under 2% too, because a mutation is one dict op under a
  per-metric lock against millisecond-scale device work);
* **get-or-create registration** — modules declare their own metrics at
  import; two modules naming the same series share one instance, so the
  serving engine and the continuous batcher can feed the same latency
  histogram without importing each other.

``parse_text`` is the matching strict parser (used by the inspect CLI's
``--metrics`` mode and by tests as the exposition-format oracle).
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

#: Prometheus text exposition content type (version is part of the
#: format contract scrapers negotiate on).
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Default buckets for latency histograms, in seconds: sub-ms lanes for
#: on-chip ticks through multi-second lanes for tunnel-attached RPCs.
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Global telemetry switch (metrics AND tracing).  The disabled path
    is one flag check per instrumentation site."""
    global _enabled
    _enabled = bool(on)


def _labelkey(labels: dict) -> tuple:
    return tuple(sorted(labels.items())) if labels else ()


def _escape_label_value(v) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n").replace("\r", ""))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    return ("{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                           for k, v in key) + "}")


def _fmt_value(v) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: Lock-discipline manifest (tpushare.analysis.confinement): metric
#: value stores and the registry's family table mutate only under their
#: own lock.  The ``*_locked`` method-name suffix is the documented
#: callers-hold-the-lock convention (``Histogram._state_locked``) — the
#: checker exempts those bodies.
_LOCK_GUARDED = {
    "_Metric": ("_vals",),
    "Counter": ("_vals",),
    "Gauge": ("_vals",),
    "Histogram": ("_vals",),
    "Registry": ("_metrics",),
}


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        #: declared label names (the family's schema: what docs/METRICS.md
        #: catalogs and the label-hygiene lint checks observations
        #: against); () = unlabeled family
        self.labelnames: Tuple[str, ...] = ()
        self._lock = threading.Lock()
        self._vals: dict = {}

    def clear(self) -> None:
        """Drop every labeled series (e.g. before re-mirroring gauges
        whose label sets churn, like per-tenant usage)."""
        with self._lock:
            self._vals.clear()

    def samples(self) -> List[Tuple[str, tuple, float]]:
        """[(series_name, label_key, value)] — the exposition lines."""
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def inc(self, by: float = 1, **labels) -> None:
        if not _enabled:
            return
        key = _labelkey(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + by

    def value(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_labelkey(labels), 0.0)

    def samples(self):
        with self._lock:
            return [(self.name, k, v) for k, v in sorted(self._vals.items())]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not _enabled:
            return
        with self._lock:
            self._vals[_labelkey(labels)] = float(value)

    def add(self, by: float = 1, **labels) -> None:
        if not _enabled:
            return
        key = _labelkey(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + by

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._vals.get(_labelkey(labels))

    def samples(self):
        with self._lock:
            return [(self.name, k, v) for k, v in sorted(self._vals.items())]


def quantile_from_buckets(bounds: List[float], cum_counts: List[float],
                          q: float) -> Optional[float]:
    """Quantile estimate from cumulative histogram buckets.

    ``bounds`` are the finite upper bounds (ascending); ``cum_counts``
    the cumulative counts per bucket PLUS the +Inf bucket (so
    ``len(cum_counts) == len(bounds) + 1``).  Linear interpolation
    within the winning bucket, like PromQL's ``histogram_quantile``;
    values in the +Inf bucket clamp to the largest finite bound.
    """
    if not cum_counts:
        return None
    total = cum_counts[-1]
    if total <= 0:
        return None
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in zip(bounds, cum_counts):
        if cum >= target:
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return bound
            frac = (target - prev_cum) / in_bucket
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, cum
    return bounds[-1] if bounds else None


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")

    def _state_locked(self, key: tuple) -> list:
        """Get-or-init one label set's ``[bucket counts (+Inf last),
        sum]`` state — callers hold ``self._lock`` (the ONE copy of the
        state-shape invariant, shared by the three observe flavors)."""
        st = self._vals.get(key)
        if st is None:
            st = self._vals[key] = [[0] * (len(self.buckets) + 1), 0.0]
        return st

    def observe(self, value: float, **labels) -> None:
        if not _enabled:
            return
        key = _labelkey(labels)
        with self._lock:
            st = self._state_locked(key)
            st[0][bisect_left(self.buckets, value)] += 1
            st[1] += value

    def observe_n(self, value: float, n: int, **labels) -> None:
        """``n`` observations of the same ``value`` under ONE lock —
        the fan-out fast path for per-request attribution, where a
        batch dispatch credits an identical share to every request it
        carried (one lock instead of batch-size locks on the serving
        hot path)."""
        if not _enabled or n <= 0:
            return
        key = _labelkey(labels)
        with self._lock:
            st = self._state_locked(key)
            st[0][bisect_left(self.buckets, value)] += n
            st[1] += value * n

    def observe_many(self, values, **labels) -> None:
        """A batch of distinct observations under ONE lock — the other
        per-request fast path (a delivered batch observes batch-size
        latencies at once; per-value ``observe`` calls would pay a lock
        round trip each inside the serving loop)."""
        if not _enabled or not values:
            return
        key = _labelkey(labels)
        with self._lock:
            st = self._state_locked(key)
            counts, buckets = st[0], self.buckets
            total = 0.0
            for v in values:
                counts[bisect_left(buckets, v)] += 1
                total += v
            st[1] += total

    def count(self, **labels) -> int:
        with self._lock:
            st = self._vals.get(_labelkey(labels))
            return sum(st[0]) if st else 0

    def sum(self, **labels) -> float:
        with self._lock:
            st = self._vals.get(_labelkey(labels))
            return st[1] if st else 0.0

    def quantile(self, q: float, **labels) -> Optional[float]:
        with self._lock:
            st = self._vals.get(_labelkey(labels))
            if st is None:
                return None
            counts = list(st[0])
        cum, acc = [], 0.0
        for c in counts:
            acc += c
            cum.append(acc)
        return quantile_from_buckets(list(self.buckets), cum, q)

    def samples(self):
        out = []
        with self._lock:
            items = sorted(self._vals.items())
            for key, (counts, total) in items:
                acc = 0
                for bound, c in zip(self.buckets, counts):
                    acc += c
                    out.append((self.name + "_bucket",
                                key + (("le", _fmt_value(bound)),), acc))
                acc += counts[-1]
                out.append((self.name + "_bucket",
                            key + (("le", "+Inf"),), acc))
                out.append((self.name + "_sum", key, total))
                out.append((self.name + "_count", key, acc))
        return out


class Registry:
    """Name -> metric; get-or-create with kind checking."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help_text: str,
             labels: Tuple[str, ...] = (), **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_text, **kw)
                m.labelnames = tuple(labels)
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, not {cls.kind}")
            elif labels and not m.labelnames:
                # get-or-create: a later registration may carry the
                # declaration an earlier anonymous one omitted
                m.labelnames = tuple(labels)
            elif labels and tuple(labels) != m.labelnames:
                # a CONFLICTING declaration is a schema bug, loud like
                # the kind mismatch above — silently keeping the first
                # would publish a wrong catalog/lint schema
                raise ValueError(
                    f"metric {name!r} already declared with labels "
                    f"{m.labelnames}, not {tuple(labels)}")
            return m

    def counter(self, name: str, help_text: str,
                labels: Tuple[str, ...] = ()) -> Counter:
        return self._get(Counter, name, help_text, labels=labels)

    def gauge(self, name: str, help_text: str,
              labels: Tuple[str, ...] = ()) -> Gauge:
        return self._get(Gauge, name, help_text, labels=labels)

    def histogram(self, name: str, help_text: str,
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
                  labels: Tuple[str, ...] = ()) -> Histogram:
        return self._get(Histogram, name, help_text, buckets=buckets,
                         labels=labels)

    def describe(self) -> List[Tuple[str, str, str]]:
        """[(name, kind, help)] for every registered family — the lint
        test's view of the namespace."""
        with self._lock:
            return [(m.name, m.kind, m.help)
                    for m in sorted(self._metrics.values(),
                                    key=lambda m: m.name)]

    def find(self, name: str) -> Optional[_Metric]:
        """Read-only lookup: the registered family, or None — for
        readers (usage reporting) that must not get-or-create a family
        with placeholder metadata just to peek at its value."""
        with self._lock:
            return self._metrics.get(name)

    def families(self) -> List[Tuple[str, str, str, Tuple[str, ...]]]:
        """[(name, kind, help, labelnames)] — the metric catalog's view
        (docs/METRICS.md) and the label-hygiene lint's schema source."""
        with self._lock:
            return [(m.name, m.kind, m.help, m.labelnames)
                    for m in sorted(self._metrics.values(),
                                    key=lambda m: m.name)]

    def render(self) -> str:
        """Prometheus text format: HELP + TYPE + samples per family."""
        lines = []
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        for m in metrics:
            help_text = (m.help.replace("\\", r"\\").replace("\n", r"\n"))
            lines.append(f"# HELP {m.name} {help_text}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for series, key, val in m.samples():
                lines.append(f"{series}{_fmt_labels(key)} {_fmt_value(val)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every metric (keeps registrations) — test isolation."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()


#: the process-global registry every instrumentation site feeds
REGISTRY = Registry()


def counter(name: str, help_text: str,
            labels: Tuple[str, ...] = ()) -> Counter:
    return REGISTRY.counter(name, help_text, labels=labels)


def gauge(name: str, help_text: str,
          labels: Tuple[str, ...] = ()) -> Gauge:
    return REGISTRY.gauge(name, help_text, labels=labels)


def histogram(name: str, help_text: str,
              buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
              labels: Tuple[str, ...] = ()) -> Histogram:
    return REGISTRY.histogram(name, help_text, buckets=buckets,
                              labels=labels)


# --------------------------------------------------------------------------
# Strict exposition-format parser (inspect --metrics + test oracle)
# --------------------------------------------------------------------------
_SERIES_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # series name
    r"(?:\{(.*)\})?"                        # optional label block
    r" (\+?Inf|-Inf|NaN|[0-9eE.+-]+)$")     # value
_LABEL_RE = re.compile(
    r'^([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(?:,|$)')
_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPE_MAP = {"n": "\n", "\\": "\\", '"': '"'}


def _unescape_label_value(raw: str) -> str:
    # ONE left-to-right pass: sequential str.replace would corrupt a
    # literal backslash-then-n ('a\\nb' escapes to 'a\\\\nb'; replacing
    # '\\n' first would misread the second backslash as starting '\\n')
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), "\\" + m.group(1)), raw)


def _parse_labels(block: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    rest = block
    while rest:
        m = _LABEL_RE.match(rest)
        if not m:
            raise ValueError(f"malformed label block: {block!r}")
        labels[m.group(1)] = _unescape_label_value(m.group(2))
        rest = rest[m.end():]
    return labels


def parse_text(text: str) -> dict:
    """Parse Prometheus text exposition into
    ``{"meta": {family: {"type": t, "help": h}},
       "samples": {series: [(labels, value), ...]}}``.

    Raises ``ValueError`` on any malformed line — strict on purpose, so
    tests using it genuinely validate the exposition format.
    """
    meta: Dict[str, dict] = {}
    samples: Dict[str, list] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            meta.setdefault(parts[0], {})["help"] = (
                parts[1] if len(parts) > 1 else "")
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ", 1)
            if len(parts) != 2 or parts[1] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE line: {line!r}")
            meta.setdefault(parts[0], {})["type"] = parts[1]
            continue
        if line.startswith("#"):
            continue        # comment
        m = _SERIES_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        labels = _parse_labels(m.group(2)) if m.group(2) else {}
        samples.setdefault(m.group(1), []).append(
            (labels, float(m.group(3))))
    return {"meta": meta, "samples": samples}
