"""Bounded ring-buffer event tracer emitting Chrome trace-event JSON.

Request-path spans (submit -> batch -> dispatch -> deliver, Allocate
RPCs, kubelet queries) land in a fixed-capacity deque; ``to_chrome()``
renders the buffer as a Chrome/Perfetto trace-event object
(``{"traceEvents": [...]}``) that ``chrome://tracing`` / ui.perfetto.dev
load directly.  The daemon serves it at ``/debug/trace``.

A RING buffer, not a log: tracing stays permanently on without an
unbounded-memory or an I/O cost — old events fall off the back, and a
dump shows the most recent window of activity, which is the window an
operator debugging "why is serving slow RIGHT NOW" wants.  Span enter/
exit is two ``perf_counter`` reads and one deque append (lock-held
nanoseconds); when telemetry is disabled ``span()`` returns a shared
no-op context, so the disabled path is one flag check.

Fleet-merge support (docs/TRACING.md): every event carries a monotonic
``seq`` so ``/debug/trace?since=<seq>`` tails the ring incrementally
(the ``/debug/events`` cursor contract), and ``to_chrome()`` attaches a
``tpushareClock`` key — this process's wall time paired with its
``perf_counter`` reading at dump time — so the fleet scraper can rebase
each process's private monotonic epoch onto one timeline (extra
top-level keys are ignored by Perfetto; event ``ts`` stays local).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import List

from . import registry

#: Lock-discipline manifest (tpushare.analysis.confinement): ring and
#: sequence mutations happen only under the tracer's own lock.
_LOCK_GUARDED = {
    "Tracer": ("_buf", "_seq"),
}


class _NullSpan:
    """Shared no-op context for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        tr = self._tracer
        now = time.perf_counter()
        tr._emit({
            "name": self.name, "cat": self.cat, "ph": "X",
            "ts": (self._t0 - tr._epoch) * 1e6,
            "dur": (now - self._t0) * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": self.args,
        })
        return False


class Tracer:
    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._epoch = time.perf_counter()
        self._seq = 0

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._buf.maxlen or 0

    def set_capacity(self, capacity: int) -> None:
        # lock held around the swap: a concurrent _emit must append to
        # either the old or the new deque, never to a dropped one (the
        # shrink-while-emitting race; threaded regression in
        # tests/test_telemetry.py)
        with self._lock:
            self._buf = collections.deque(self._buf, maxlen=capacity)

    def _emit(self, event: dict) -> None:
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._buf.append(event)

    def span(self, name: str, cat: str = "tpushare", **args):
        """Context manager recording one complete ("X") event on exit.
        ``args`` must be JSON-serializable (they ride into the dump)."""
        if not registry.enabled():
            return _NULL
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "tpushare", **args) -> None:
        """One thread-scoped instant ("i") event."""
        if not registry.enabled():
            return
        self._emit({
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": (time.perf_counter() - self._epoch) * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": args,
        })

    def events(self) -> List[dict]:
        """Snapshot, oldest first."""
        with self._lock:
            return list(self._buf)

    def events_since(self, seq: int) -> List[dict]:
        """Events with ``seq`` strictly greater than the cursor, oldest
        first — the ``/debug/trace?since=`` incremental tail (same
        contract as the flight recorder's: a cursor that has fallen off
        the back simply returns the whole ring; the seq gap tells the
        scraper how much it lost)."""
        with self._lock:
            return [e for e in self._buf if e["seq"] > seq]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def to_chrome(self, since: int = 0) -> dict:
        """The Chrome trace-event JSON object /debug/trace serves.
        The ``tpushareClock`` key (ignored by trace viewers) pins this
        process's private monotonic epoch to wall time AT DUMP TIME —
        an event's wall time is ``wall_time_s - (trace_time_us -
        ts) / 1e6`` — which is what lets the fleet scraper merge dumps
        from processes with unrelated ``perf_counter`` bases onto one
        timeline (durations are epoch-free and survive any rebase)."""
        events = self.events_since(since) if since else self.events()
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "tpushareClock": {
                "pid": os.getpid(),
                "wall_time_s": time.time(),
                "trace_time_us": (time.perf_counter() - self._epoch)
                * 1e6,
            },
        }


#: the process-global tracer every span site feeds
TRACER = Tracer()


from ..utils.httpserver import with_query  # noqa: E402 (stdlib-only)


@with_query
def debug_trace_route(_body=None, query=None):
    """Drop-in JsonHTTPServer handler: GET /debug/trace[?since=<seq>]
    off :data:`TRACER` — the whole ring as Chrome trace JSON by
    default, or only events past the cursor (the ``debug_events_route``
    tailing contract), each dump stamped with the clock anchor the
    fleet merge needs.  One shared implementation for the daemon, the
    LLM server, and the router."""
    try:
        since = int((query or {}).get("since", 0))
    except (TypeError, ValueError):
        return 400, {"Error": "since must be an integer seq cursor"}
    return 200, TRACER.to_chrome(since=since)
