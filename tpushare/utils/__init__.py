"""Shared utilities: native shim loader, logging, stack dumps."""
