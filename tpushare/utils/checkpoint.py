"""Parameter checkpointing: nested-dict pytrees ⇄ one ``.npz`` file.

The control plane is deliberately stateless (SURVEY.md §5: all allocation
state lives in the cluster); checkpointing is a *workload*-side need —
model params (including int8 QTensors) saved atomically so a serving pod
restarted by the scheduler reloads instead of re-initializing.

Keys are ``/``-joined paths of the nested dicts; arrays round-trip with
dtype (bf16 stored via uint16 view, which npz cannot hold natively).
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

_BF16_SUFFIX = "__bf16"


def _flatten(tree, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
        return out
    key = prefix[:-1]
    arr = np.asarray(tree)
    if arr.dtype == jnp.bfloat16:
        out[key + _BF16_SUFFIX] = arr.view(np.uint16)
    else:
        out[key] = arr
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> dict:
    root: dict = {}
    for key, arr in flat.items():
        if key.endswith(_BF16_SUFFIX):
            key = key[: -len(_BF16_SUFFIX)]
            arr = arr.view(jnp.bfloat16)
        node = root
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(arr)
    return root


def save_params(path: str, params: dict) -> None:
    """Atomic save (write temp + rename) of a nested-dict param pytree."""
    flat = _flatten(params)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_params(path: str) -> dict:
    with np.load(path) as z:
        return _unflatten({k: z[k] for k in z.files})


# ---------------------------------------------------------------------------
# Full train-state checkpointing (arbitrary pytrees, sharded arrays): orbax
# ---------------------------------------------------------------------------
def save_train_state(ckpt_dir: str, state) -> None:
    """One-shot save of an arbitrary pytree to a FRESH directory.

    Refuses to overwrite: orbax's overwrite (``force=True``) deletes the
    old checkpoint before committing the new one, leaving a crash window
    that loses all state.  Periodic checkpointing must use
    :func:`make_checkpoint_manager` (step-numbered dirs, retention), which
    never deletes the old step before the new one is committed.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(ckpt_dir)
    if os.path.exists(path):
        raise FileExistsError(
            f"{path} exists; use make_checkpoint_manager for periodic "
            f"checkpointing (atomic across overwrites)")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state)


def load_train_state(ckpt_dir: str, like=None):
    """Restore; pass ``like`` (a matching abstract/concrete pytree) to get
    exact structure and shardings back."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(os.path.abspath(ckpt_dir), like)
        return ckptr.restore(os.path.abspath(ckpt_dir))


def make_checkpoint_manager(ckpt_dir: str, max_to_keep: int = 3):
    """Step-numbered checkpoint manager (the crash-safe periodic form)."""
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(ckpt_dir),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))


def save_step(mgr, step: int, state, wait: bool = True) -> None:
    """Save ``state`` as step ``step`` through a manager from
    :func:`make_checkpoint_manager` (all orbax API contact lives here)."""
    import orbax.checkpoint as ocp

    mgr.save(step, args=ocp.args.StandardSave(state))
    if wait:
        mgr.wait_until_finished()


def restore_latest(mgr, like):
    """(step, state) for the manager's latest checkpoint, restored against
    an abstract/concrete ``like`` pytree; (None, None) when empty."""
    import orbax.checkpoint as ocp

    step = mgr.latest_step()
    if step is None:
        return None, None
    return step, mgr.restore(step, args=ocp.args.StandardRestore(like))
