"""Token-batch input pipeline for training.

Deliberately simple and TPU-shaped: fixed-shape [batch, seq+1] windows
(inputs+targets overlap by one), deterministic per-epoch shuffling keyed
by (seed, epoch) so every host of a dp group can derive ITS shard of
each global batch independently — no data service, no host-to-host
coordination, resumable from (epoch, step) alone.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class DataConfig:
    batch: int
    seq: int          # model sequence length; windows are seq+1 tokens
    seed: int = 0
    drop_remainder: bool = True


class TokenDataset:
    """Contiguous token ids (np.memmap or array) -> shuffled LM windows."""

    def __init__(self, tokens: np.ndarray, cfg: DataConfig):
        if tokens.ndim != 1:
            raise ValueError("tokens must be a 1-D id array")
        self.tokens = tokens
        self.cfg = cfg
        self.window = cfg.seq + 1
        self.n_windows = len(tokens) // self.window
        if self.n_windows < cfg.batch:
            raise ValueError(
                f"{len(tokens)} tokens yield {self.n_windows} windows "
                f"< batch {cfg.batch}")
        # reshape view (no copy even over a memmap): batch assembly is one
        # fancy index instead of a per-row python loop
        self._windows = tokens[: self.n_windows * self.window].reshape(
            self.n_windows, self.window)

    def _order(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng((self.cfg.seed, epoch))
        return rng.permutation(self.n_windows)

    def batches(self, epoch: int = 0,
                start_step: int = 0,
                dp_rank: int = 0, dp_size: int = 1
                ) -> Iterator[np.ndarray]:
        """Yield [batch/dp_size, seq+1] shards of each global batch.

        ``start_step`` skips already-consumed batches after a resume.
        """
        if self.cfg.batch % dp_size:
            raise ValueError(f"batch {self.cfg.batch} not divisible by "
                             f"dp_size {dp_size}")
        per_host = self.cfg.batch // dp_size
        order = self._order(epoch)
        n_batches = self.n_windows // self.cfg.batch
        for b in range(start_step, n_batches):
            idx = order[b * self.cfg.batch:(b + 1) * self.cfg.batch]
            mine = idx[dp_rank * per_host:(dp_rank + 1) * per_host]
            yield self._windows[mine]

    def epochs(self, dp_rank: int = 0, dp_size: int = 1,
               start_epoch: int = 0, start_step: int = 0
               ) -> Iterator[np.ndarray]:
        """Endless stream across epochs, resumable at (epoch, step)."""
        epoch = start_epoch
        step = start_step
        while True:
            yield from self.batches(epoch, start_step=step,
                                    dp_rank=dp_rank, dp_size=dp_size)
            epoch += 1
            step = 0
