"""Shared threaded-HTTP scaffold for the daemon's small endpoints.

One lifecycle implementation (bind, port readback, daemon thread,
start/stop) for the status endpoint and the scheduler extender, so
hardening fixes land once.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qsl


def with_query(handler: Callable) -> Callable:
    """Mark a route handler as query-aware: it is called
    ``handler(body, query)`` with ``query`` a flat {name: last value}
    dict parsed from the URL's query string (``/debug/events?since=42``
    -> ``{"since": "42"}``).  Un-marked handlers keep the one-argument
    ``handler(body)`` contract, so existing routes need no change."""
    handler.wants_query = True
    return handler


class StreamingBody:
    """Marks a handler payload as a STREAM: ``chunks`` yields byte
    strings written (and flushed) one at a time, with no Content-Length
    — the body ends when the handler closes the connection (HTTP/1.0
    delimiting, which every client speaks).  Used for NDJSON token
    streaming from the LLM server."""

    def __init__(self, chunks, content_type: str = "application/x-ndjson"):
        self.chunks = chunks
        self.content_type = content_type


class RawBody:
    """A handler payload with an explicit content type — for responses
    whose media type carries protocol meaning (Prometheus' ``/metrics``
    negotiates on ``text/plain; version=0.0.4``)."""

    def __init__(self, data, content_type: str = "text/plain; charset=utf-8"):
        self.data = data.encode() if isinstance(data, str) else data
        self.content_type = content_type


class JsonHTTPServer:
    """Routes: {(method, path): handler}; handler(body_dict|None) ->
    (code, payload) or (code, payload, headers_dict).  Payload str ->
    text/plain, RawBody -> explicit content type, StreamingBody ->
    incremental write, else JSON; the optional headers dict adds
    response headers (e.g. Retry-After on a policy 429)."""

    def __init__(self, port: int, addr: str,
                 routes: dict,
                 auth_token: Optional[str] = None,
                 inband_errors: bool = False):
        # inband_errors: report handler exceptions as HTTP 200 with an
        # {"Error": ...} body. That is the scheduler-extender webhook
        # protocol (kube-scheduler reads the Error field and treats a
        # non-200 as a transport failure); every other server wants a
        # plain 500 so status-code-checking clients see the failure.
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, payload, headers=None) -> None:
                def _extra_headers():
                    for k, v in (headers or {}).items():
                        self.send_header(k, str(v))

                if isinstance(payload, StreamingBody):
                    try:
                        # the header writes sit INSIDE the guarded
                        # region: a client gone before headers must
                        # still reach the finally, or stream-side
                        # accounting (the LLM server's in-flight
                        # counter) leaks on exactly that disconnect
                        self.send_response(code)
                        _extra_headers()
                        self.send_header("Content-Type",
                                         payload.content_type)
                        # no Content-Length: body delimited by close
                        self.end_headers()
                        for chunk in payload.chunks:
                            self.wfile.write(chunk)
                            self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        pass            # client went away mid-stream
                    finally:
                        # Deterministically close the generator so its
                        # finally-cleanup (e.g. the LLM server's
                        # cancel-on-disconnect) runs NOW, not at gc.
                        close = getattr(payload.chunks, "close", None)
                        if close is not None:
                            close()
                    self.close_connection = True
                    return
                if isinstance(payload, RawBody):
                    data = payload.data
                    ctype = payload.content_type
                elif isinstance(payload, str):
                    data = payload.encode()
                    ctype = "text/plain; charset=utf-8"
                else:
                    data = json.dumps(payload).encode()
                    ctype = "application/json"
                self.send_response(code)
                _extra_headers()
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _authorized(self) -> bool:
                if outer.auth_token is None:
                    return True
                got = self.headers.get("Authorization", "")
                return got == f"Bearer {outer.auth_token}"

            def _dispatch(self, method: str):
                if not self._authorized():
                    self._send(401, {"Error": "unauthorized"})
                    return
                # route on the bare path: the query string is handler
                # input (?since= cursors), not part of the route key
                path, _, rawq = self.path.partition("?")
                handler = outer.routes.get((method, path))
                if handler is None:
                    self._send(404, {"Error": "not found"})
                    return
                body = None
                if method == "POST":
                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                    except json.JSONDecodeError:
                        self._send(400, {"Error": "bad json"})
                        return
                headers = None
                try:
                    if getattr(handler, "wants_query", False):
                        result = handler(body, dict(parse_qsl(rawq)))
                    else:
                        result = handler(body)
                    if len(result) == 3:
                        # (code, payload, headers) — responses whose
                        # HEADERS carry protocol meaning (the policy
                        # layer's 429 + Retry-After)
                        code, payload, headers = result
                    else:
                        code, payload = result
                except Exception as e:  # keep serving either way
                    code = 200 if outer.inband_errors else 500
                    payload = {"Error": str(e)}
                self._send(code, payload, headers)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

        self.routes = routes
        self.auth_token = auth_token
        self.inband_errors = inband_errors
        self._server = ThreadingHTTPServer((addr, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="tpushare-http")

    def start(self) -> "JsonHTTPServer":
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
