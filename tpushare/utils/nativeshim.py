"""ctypes loader for the native libtpu discovery shim.

The shim (``native/tpushim.c`` -> ``tpushare/_native/libtpushim.so``) is the
TPU analog of the reference's vendored NVML cgo binding + ``nvml_dl.c``
dlopen shim: a thin C layer that dlopens ``libtpu.so`` at *runtime* so the
Python daemon imports and runs on nodes without a TPU driver (CI, laptops).

Absence of the compiled shim is not an error — callers fall back to
metadata discovery, mirroring how the reference binary links with
``--unresolved-symbols=ignore-in-object-files`` (Dockerfile:6).
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
from typing import Dict, Optional

log = logging.getLogger("tpushare.nativeshim")

_DEFAULT_PATHS = (
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "_native",
                 "libtpushim.so"),
    "libtpushim.so",
)


class Shim:
    """Typed wrapper over libtpushim.so."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.tpushim_init.restype = ctypes.c_int
        lib.tpushim_shutdown.restype = None
        lib.tpushim_chip_count.restype = ctypes.c_int
        lib.tpushim_chip_info_json.restype = ctypes.c_char_p
        lib.tpushim_chip_info_json.argtypes = [ctypes.c_int]
        lib.tpushim_version.restype = ctypes.c_char_p
        # older prebuilt shims may lack the event surface; degrade to
        # "no native events" instead of failing to load
        self._has_events = hasattr(lib, "tpushim_poll_events_json")
        if self._has_events:
            lib.tpushim_poll_events_json.restype = ctypes.c_char_p

    def init(self) -> bool:
        """True iff libtpu.so was dlopen-able and initialized."""
        return bool(self._lib.tpushim_init())

    def shutdown(self) -> None:
        self._lib.tpushim_shutdown()

    def version(self) -> str:
        return self._lib.tpushim_version().decode()

    def chip_count(self) -> int:
        return max(0, int(self._lib.tpushim_chip_count()))

    def chip_info(self, index: int) -> Dict:
        raw = self._lib.tpushim_chip_info_json(index)
        if not raw:
            return {}
        try:
            return json.loads(raw.decode())
        except json.JSONDecodeError:
            return {}

    def poll_events(self) -> list:
        """Health TRANSITIONS since the last poll:
        ``[{"chip": N|-1, "healthy": bool, "reason": str}, ...]`` — the
        shim open()-probes each device node (catching present-but-wedged
        chips an existence check misses) and re-stats the libtpu runtime
        file (chip -1 = unattributable)."""
        if not self._has_events:
            return []
        raw = self._lib.tpushim_poll_events_json()
        if not raw:
            return []
        try:
            out = json.loads(raw.decode())
            return out if isinstance(out, list) else []
        except json.JSONDecodeError:
            return []


def load(path: Optional[str] = None) -> Optional[Shim]:
    """Load the shim; None when it is not built/present (soft dependency)."""
    candidates = (path,) if path else _DEFAULT_PATHS
    for cand in candidates:
        if cand is None:
            continue
        try:
            return Shim(ctypes.CDLL(cand))
        except OSError:
            continue
        except AttributeError:
            # A library by that name exists but lacks the tpushim_* surface
            # (stale or foreign .so) — treat as absent, don't crash the daemon.
            log.warning("%s is not a tpushim library; ignoring", cand)
            continue
    log.debug("libtpushim.so not found (tried %s)", candidates)
    return None
