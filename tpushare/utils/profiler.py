"""Lightweight tracing/profiling helpers (aux-subsystem parity-plus).

The reference's only tracing is a SIGQUIT stack dump; tpushare keeps
that (``stackdump``) and adds: a ``jax.profiler`` trace context for
TensorBoard-consumable device traces, and a step timer that separates
compile (first call) from steady-state.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Optional


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a jax device trace viewable in TensorBoard/XProf."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def _sync(result) -> None:
    """Drain the device stream by host-fetching ONE scalar from the
    first array leaf of ``result`` — the sanctioned barrier:
    ``block_until_ready`` has returned early on the remote axon backend
    (CLAUDE.md), while executions are in-order per device, so a single
    element fetch waits for everything queued before it."""
    import jax

    for leaf in jax.tree_util.tree_leaves(result):
        if hasattr(leaf, "ndim"):
            # first-element index, no reshape: reshape is its own
            # device dispatch (~70ms RPC each on the tunnel), which
            # would inflate every sample by a second round trip
            float(leaf[(0,) * leaf.ndim])
            return


def time_fn(fn: Callable, *args, iters: int = 10,
            warmup: int = 1) -> Dict[str, float]:
    """{'compile_s', 'mean_s', 'p50_s', 'best_s'} for a jitted callable.

    The first call is measured separately: under jit it includes trace +
    XLA compile, which steady-state numbers must exclude.
    """
    t0 = time.perf_counter()
    _sync(fn(*args))
    compile_s = time.perf_counter() - t0
    for _ in range(max(0, warmup - 1)):
        _sync(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return {
        "compile_s": compile_s,
        "mean_s": sum(samples) / len(samples),
        "p50_s": samples[len(samples) // 2],
        "best_s": samples[0],
    }
