"""All-thread stack dump, the reference's SIGQUIT goroutine dump
(``pkg/gpu/nvidia/coredump.go:10-30``) in Python form."""

from __future__ import annotations

import sys
import time
import traceback


def stack_trace() -> str:
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"\n--- thread {tid} ---\n")
        out.append("".join(traceback.format_stack(frame)))
    return "".join(out)


def dump(dir_path: str = "/etc/kubernetes") -> str:
    path = f"{dir_path}/tpushare_stack_{int(time.time())}.txt"
    try:
        with open(path, "w") as f:
            f.write(stack_trace())
        return path
    except OSError:
        sys.stderr.write(stack_trace())
        return "<stderr>"
