"""Shared parsing of ``jax.tree_util.keystr`` paths.

One canonical place to turn "['layers'][0]['wq']" (or "layers/0/wq")
into key components — both the sharding rules and the quantizer match on
these, and two private copies would drift when keystr's format changes.
"""

from __future__ import annotations

from typing import List


def components(path: str) -> List[str]:
    norm = path.replace("[", "/").replace("]", "").replace("'", "")
    return [p for p in norm.split("/") if p]


def leaf_key(path: str) -> str:
    """Last component ('wq')."""
    parts = components(path)
    return parts[-1] if parts else ""


def param_key(path: str) -> str:
    """The parameter-name component: the last one, except that WRAPPED
    leaves one level down — quantized ({'q','q4','s'}) and/or LoRA
    ({'w','a','b','scale'}) — report their parent ('wq', not 'q'/'w')
    so they inherit its sharding rule (spec legalization right-aligns
    and drops non-dividing axes, so the small adapter dims degrade to
    replication where the rule doesn't fit)."""
    parts = components(path)
    # 'scale' stays itself (a tiny per-layer vector; replicate) — the
    # weight-sized members inherit the parent's rule
    if len(parts) >= 2 and parts[-1] in ("q", "q4", "s", "w", "a", "b"):
        return parts[-2]
    return parts[-1] if parts else ""
